// apex_tpu native runtime — host-side data-plane ops.
//
// TPU-native counterpart of the reference's host/C++ layer: apex_C
// flatten/unflatten (csrc/flatten_unflatten.cpp:1-17 — bucket coalescing for
// gradient exchange and checkpoint assembly) and the byte-work half of the
// examples' data_prefetcher (examples/imagenet/main_amp.py:264-302 — the
// side-stream uint8→float normalize + NHWC→NCHW layout change).  On TPU the
// device-side halves of both jobs belong to XLA (concat fusion, infeed), but
// the HOST halves are real CPU work on the input path and are implemented
// natively here: multi-threaded coalesce/scatter and fused
// normalize-transpose, exposed over a plain C ABI consumed via ctypes
// (apex_tpu/runtime/__init__.py).
//
// Built with: g++ -O3 -march=native -shared -fPIC -pthread runtime.cpp
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) over a small thread pool.  Spawn cost is
// irrelevant against the multi-MB memcpy/convert bodies this serves.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (n <= 0) return;
  int t = threads;
  if (t <= 0) t = static_cast<int>(std::thread::hardware_concurrency());
  if (t > n) t = static_cast<int>(n);
  if (t <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next{0};
  std::vector<std::thread> pool;
  pool.reserve(t);
  for (int w = 0; w < t; ++w) {
    pool.emplace_back([&] {
      for (int64_t i; (i = next.fetch_add(1)) < n;) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Coalesce n buffers (nbytes[i] each) into dst, end to end.  The apex_C
// `flatten` semantic (csrc/flatten_unflatten.cpp:5-8) minus torch: offsets
// are the running byte sums, computed identically by the Python binding.
void apex_flatten(const void** srcs, const int64_t* nbytes, int64_t n,
                  void* dst, int threads) {
  std::vector<int64_t> off(static_cast<size_t>(n) + 1, 0);
  for (int64_t i = 0; i < n; ++i) off[i + 1] = off[i] + nbytes[i];
  auto* out = static_cast<uint8_t*>(dst);
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(out + off[i], srcs[i], static_cast<size_t>(nbytes[i]));
  });
}

// Scatter flat back into n buffers — apex_C `unflatten`
// (csrc/flatten_unflatten.cpp:10-13).
void apex_unflatten(const void* flat, void** dsts, const int64_t* nbytes,
                    int64_t n, int threads) {
  std::vector<int64_t> off(static_cast<size_t>(n) + 1, 0);
  for (int64_t i = 0; i < n; ++i) off[i + 1] = off[i] + nbytes[i];
  auto* in = static_cast<const uint8_t*>(flat);
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(dsts[i], in + off[i], static_cast<size_t>(nbytes[i]));
  });
}

// Fused uint8 NHWC → float32 NCHW with per-channel (x/255 - mean)/std —
// exactly the arithmetic the reference prefetcher runs per batch on its side
// stream (main_amp.py:287-301: sub_(mean).div_(std) after a 255-scale
// normalize folded into mean/std there; we take mean/std in [0,1] units).
void apex_normalize_u8_nhwc_to_f32_nchw(const uint8_t* src, float* dst,
                                        int64_t n, int64_t h, int64_t w,
                                        int64_t c, const float* mean,
                                        const float* stdv, int threads) {
  const int64_t hw = h * w;
  std::vector<float> scale(static_cast<size_t>(c)), bias(
      static_cast<size_t>(c));
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stdv[ch]);
    bias[ch] = -mean[ch] / stdv[ch];
  }
  parallel_for(n * c, threads, [&](int64_t job) {
    const int64_t img = job / c, ch = job % c;
    const uint8_t* s = src + img * hw * c + ch;
    float* d = dst + img * c * hw + ch * hw;
    const float sc = scale[ch], bi = bias[ch];
    for (int64_t i = 0; i < hw; ++i) d[i] = s[i * c] * sc + bi;
  });
}

// Layout-preserving variant for channels-last models (nn.to_channels_last):
// uint8 NHWC → float32 NHWC, same per-channel normalize, no transpose — the
// channel sweep stays the inner (contiguous) loop on both sides.
void apex_normalize_u8_nhwc_to_f32_nhwc(const uint8_t* src, float* dst,
                                        int64_t n, int64_t h, int64_t w,
                                        int64_t c, const float* mean,
                                        const float* stdv, int threads) {
  std::vector<float> scale(static_cast<size_t>(c)), bias(
      static_cast<size_t>(c));
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stdv[ch]);
    bias[ch] = -mean[ch] / stdv[ch];
  }
  // split n*h ways (rows are layout-contiguous; channels are
  // interleaved) so small batches still fan out across cores — the
  // NCHW sibling's n*c granularity, adapted to this layout
  parallel_for(n * h, threads, [&](int64_t job) {
    const int64_t off = job * w * c;
    const uint8_t* s = src + off;
    float* d = dst + off;
    for (int64_t i = 0; i < w; ++i) {
      for (int64_t ch = 0; ch < c; ++ch) {
        d[i * c + ch] = s[i * c + ch] * scale[ch] + bias[ch];
      }
    }
  });
}

// float32 → bfloat16 (round-to-nearest-even) bulk cast: host-side half of
// feeding bf16 batches without paying an on-device cast + extra transfer.
void apex_f32_to_bf16(const float* src, uint16_t* dst, int64_t n,
                      int threads) {
  constexpr int64_t kChunk = 1 << 16;
  const int64_t chunks = (n + kChunk - 1) / kChunk;
  parallel_for(chunks, threads, [&](int64_t cidx) {
    const int64_t lo = cidx * kChunk;
    const int64_t hi = lo + kChunk < n ? lo + kChunk : n;
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t x;
      std::memcpy(&x, src + i, 4);
      const uint32_t rounding = 0x7FFF + ((x >> 16) & 1);
      if ((x & 0x7F800000) == 0x7F800000 && (x & 0x007FFFFF)) {
        dst[i] = static_cast<uint16_t>((x >> 16) | 0x0040);  // quiet NaN
      } else {
        dst[i] = static_cast<uint16_t>((x + rounding) >> 16);
      }
    }
  });
}

int apex_runtime_abi_version() { return 1; }

}  // extern "C"
