#!/bin/bash
# Staged on-chip diagnosis of the GPT seq-1024 warmup hang seen in the
# round-3 `measure_all.sh` run (watchdog_timeout at stage=warmup after
# 540s; the same config measured 211ms/step in round 2 pre-rbg-dropout,
# pre-fused-xentropy).  Each probe isolates one suspect and is cheap to
# kill early; probes run smallest-blast-radius first so a hang is
# attributed to the first failing stage, not a combination.
set -u
cd "$(dirname "$0")"
LOG="${DIAG_LOG:-diagnose_gpt1024.jsonl}"

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((64, 64)); print('probe ok:', float(jnp.sum(x @ x)))
" 2>/dev/null
}

run() {
  name="$1"; shift
  echo "=== $name: $* ===" >&2
  # exit 4 = wedged before real work, matching bench.py's code so
  # auto_capture.sh retries this item instead of advancing past it
  if ! probe; then echo "{\"probe\": \"$name\", \"result\": \"tunnel_dead_before\"}" >>"$LOG"; exit 4; fi
  ( timeout "$DIAG_TIMEOUT" "$@" && echo "{\"probe\": \"$name\", \"result\": \"ok\"}" >>"$LOG" ) \
    || echo "{\"probe\": \"$name\", \"result\": \"failed_or_timeout\"}" >>"$LOG"
}

DIAG_TIMEOUT="${DIAG_TIMEOUT:-120}"

# 0. flash attention at S=1024, each arm alone, fwd then fwd+bwd.
#    Round 3 evidence: both the GPT seq-1024 warmup AND the kernel-timing
#    S1024 A/B hung on-chip (watchdog fired mid-shape), while S<=256
#    attention and the full GPT seq-128 step (flash engaged) measure fine.
#    Round 2 measured the same kernel at S=1024 at 211ms/step, so either
#    the tunnel wedges spontaneously under long-running jobs or something
#    environmental broke large-S flash since.
run flash1024_pallas_fwd python - <<'EOF'
import time, jax, jax.numpy as jnp, numpy as np
from apex_tpu.contrib.multihead_attn.attn_funcs import flash_attention
r = np.random.default_rng(0)
q, k, v = (jnp.asarray(r.standard_normal((4, 12, 1024, 64)), jnp.bfloat16)
           for _ in range(3))
f = jax.jit(lambda q, k, v: jnp.sum(
    flash_attention(q, k, v, causal=True).astype(jnp.float32)))
print("compiling fwd...", flush=True)
t = time.perf_counter(); val = float(f(q, k, v))
print(f"fwd compile+run {time.perf_counter()-t:.1f}s val={val:.2f}", flush=True)
t = time.perf_counter(); val = float(f(q, k, v))
print(f"fwd warm {1e3*(time.perf_counter()-t):.1f}ms", flush=True)
EOF
run flash1024_pallas_bwd python - <<'EOF'
import time, jax, jax.numpy as jnp, numpy as np
from apex_tpu.contrib.multihead_attn.attn_funcs import flash_attention
r = np.random.default_rng(0)
q, k, v = (jnp.asarray(r.standard_normal((4, 12, 1024, 64)), jnp.bfloat16)
           for _ in range(3))
f = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
    flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2),
    argnums=(0, 1, 2)))
print("compiling fwd+bwd...", flush=True)
t = time.perf_counter(); g = f(q, k, v)
val = float(jnp.sum(g[0].astype(jnp.float32)))
print(f"bwd compile+run {time.perf_counter()-t:.1f}s val={val:.2f}", flush=True)
t = time.perf_counter(); g = f(q, k, v); val = float(jnp.sum(g[0].astype(jnp.float32)))
print(f"bwd warm {1e3*(time.perf_counter()-t):.1f}ms", flush=True)
EOF
run flash1024_xla_arm env APEX_TPU_PALLAS=off python - <<'EOF'
import time, jax, jax.numpy as jnp, numpy as np
from apex_tpu.contrib.multihead_attn.attn_funcs import flash_attention
r = np.random.default_rng(0)
q, k, v = (jnp.asarray(r.standard_normal((4, 12, 1024, 64)), jnp.bfloat16)
           for _ in range(3))
f = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
    flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2),
    argnums=(0, 1, 2)))
print("compiling xla-arm fwd+bwd...", flush=True)
t = time.perf_counter(); g = f(q, k, v)
val = float(jnp.sum(g[0].astype(jnp.float32)))
print(f"xla bwd compile+run {time.perf_counter()-t:.1f}s val={val:.2f}", flush=True)
t = time.perf_counter(); g = f(q, k, v); val = float(jnp.sum(g[0].astype(jnp.float32)))
print(f"xla bwd warm {1e3*(time.perf_counter()-t):.1f}ms", flush=True)
EOF

# 1. rbg alone at GPT-1024 mask shapes (and 4x larger): is the
#    RngBitGenerator HLO itself the hang?
run rbg_shapes python - <<'EOF'
import time, jax, jax.numpy as jnp
from jax import lax, random
for shape in [(16, 1024, 768), (16, 1024, 3072), (64, 1024, 3072)]:
    f = jax.jit(lambda k: lax.rng_bit_generator(k, shape, dtype=jnp.uint32)[1].sum())
    k = jnp.zeros((4,), jnp.uint32)
    t = time.perf_counter(); v = float(f(k)); dt = time.perf_counter() - t
    t = time.perf_counter(); v = float(f(k)); dt2 = time.perf_counter() - t
    print(f"rbg {shape}: compile+run {dt:.2f}s, warm {dt2*1e3:.1f}ms")
EOF

# 2. fused xentropy fwd+bwd at the (16384, 50257) loss shape.
run xentropy python - <<'EOF'
import time, jax, jax.numpy as jnp, numpy as np
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
r = np.random.default_rng(0)
logits = jnp.asarray(r.standard_normal((16384, 50257)), jnp.bfloat16)
labels = jnp.asarray(r.integers(0, 50257, (16384,)))
f = jax.jit(jax.grad(lambda l: softmax_cross_entropy_loss(l, labels, 0.0, -1, True).mean()))
t = time.perf_counter(); g = f(logits); s = float(jnp.sum(g.astype(jnp.float32))); dt = time.perf_counter() - t
t = time.perf_counter(); g = f(logits); s = float(jnp.sum(g.astype(jnp.float32))); dt2 = time.perf_counter() - t
print(f"xentropy grad 16384x50257: compile+run {dt:.2f}s, warm {dt2*1e3:.1f}ms")
EOF

# 3. full config minus one suspect each (short runs: 3 warmup + 5 iters).
#    Riskiest probes (a hang here is a mid-step kill → possible re-wedge):
#    gated behind DIAG_FULL=1 so the quick stages can run early in a
#    healthy window and these can run at the end of the capture queue.
[ "${DIAG_FULL:-0}" = "1" ] || { echo "quick stages done (DIAG_FULL=1 for full-config probes); results in $LOG" >&2; exit 0; }
DIAG_TIMEOUT=650
run gpt1024_threefry env APEX_TPU_DROPOUT_IMPL=threefry \
    python bench.py 16 --gpt --seq-len 1024 --no-kernels --iters 5 --warmup 2 --budget-s 600
run gpt1024_plainloss python bench.py 16 --gpt --seq-len 1024 --plain-loss \
    --no-kernels --iters 5 --warmup 2 --budget-s 600
# 4. the config as shipped (per-iter warmup sync now pinpoints the iter).
run gpt1024_default python bench.py 16 --gpt --seq-len 1024 \
    --no-kernels --iters 5 --warmup 2 --budget-s 600
echo "diagnosis done; results in $LOG" >&2
