#!/bin/bash
# Resumable on-chip capture queue for a flaky tunnel: probe before every
# item; on a wedged probe sleep and retry (the axon tunnel has healed
# after 2-9h in past sessions).  Items are ordered value-first/risk-last.
# bench.py exit codes: 4 = wedged before any real work (do NOT advance —
# retry the item next healthy window); 3 = internal watchdog fired mid
# work (advance; the item is suspect and gets a diagnostic JSON line).
set -u
cd "$(dirname "$0")"
CURSOR_FILE="${CAPTURE_CURSOR:-.capture_cursor}"
LOG=measurements.jsonl
# NOTE: the cursor is POSITIONAL — when editing QUEUE, restart the
# runner AND delete the cursor file unless only appending at the end.

QUEUE=(
  # diagnose prints human progress lines to stdout: route them to its own
  # log so the measurements JSONL stream stays parseable (its JSON result
  # lines go to diagnose_gpt1024.jsonl via DIAG_LOG)
  "bash diagnose_gpt1024.sh >>diagnose_stdout.log 2>&1"
  # headline configs re-measured on the shape-aware flash dispatch (the
  # round-3 numbers in BENCH_HISTORY predate it: seq-128 attention now
  # takes the XLA path, which the kernel A/B measured 1.2x faster there)
  "timeout 700 python bench.py --no-kernels"
  "timeout 700 python bench.py --bert --no-kernels"
  "timeout 700 python bench.py --gpt --no-kernels"
  "timeout 700 python bench.py --profile"
  "timeout 700 python bench.py --profile --gpt"
  "timeout 900 python bench.py --sweep 96,128,192,256 --no-kernels --budget-s 840"
  "timeout 900 python bench.py --gpt --sweep 32,64,128 --no-kernels --budget-s 840"
  "timeout 700 python bench.py --llama --no-kernels"
  "timeout 700 python bench.py --gpt-decode --no-kernels"
  "timeout 700 python bench.py --gpt-decode --int8 --no-kernels"
  "timeout 900 python bench.py --spec-decode --no-kernels --budget-s 840"
  "timeout 700 python bench.py --gpt-decode --int8 --kv-int8 --no-kernels"
  "timeout 700 python bench.py --seq2seq --no-kernels"
  "timeout 900 python bench.py --kernels-timing --budget-s 840"
  # intermediate long-seq datapoint (flash engages at 512 under the
  # new dispatch; lower-risk than the seq-1024 config that hung)
  "timeout 700 python bench.py 32 --gpt --seq-len 512 --no-kernels"
  "timeout 700 python bench.py --llama --seq-len 512 --no-kernels"
  "timeout 700 python bench.py --vit --no-kernels"
  "timeout 700 python bench.py --dcgan --no-kernels"
  "timeout 700 python bench.py --profile --llama"
  "DIAG_FULL=1 bash diagnose_gpt1024.sh >>diagnose_stdout.log 2>&1"
  # channels-last A/B arm (appended round 4: nn.to_channels_last) — the
  # conv-layout lever against the 0.28-MFU NCHW headline, plus its
  # profile attribution
  "timeout 700 python bench.py --nhwc --no-kernels"
  "timeout 700 python bench.py --profile --nhwc"
)

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((64, 64)); print('probe ok:', float(jnp.sum(x @ x)))
" 2>/dev/null
}

cursor=$(cat "$CURSOR_FILE" 2>/dev/null || echo 0)
while [ "$cursor" -lt "${#QUEUE[@]}" ]; do
  if ! probe; then
    echo "$(date -u +%H:%M:%S) tunnel wedged; sleeping 600s (cursor=$cursor)" >&2
    sleep 600
    continue
  fi
  cmd="${QUEUE[$cursor]}"
  echo "$(date -u +%H:%M:%S) === item $cursor: $cmd ===" >&2
  eval "$cmd" >>"$LOG" 2>>"$LOG.err"
  rc=$?
  if [ "$rc" -eq 4 ]; then
    echo "$(date -u +%H:%M:%S) item $cursor wedged at init (rc=4); will retry" >&2
    sleep 600
    continue
  fi
  echo "$(date -u +%H:%M:%S) item $cursor done rc=$rc" >&2
  cursor=$((cursor + 1))
  echo "$cursor" >"$CURSOR_FILE"
done
echo "$(date -u +%H:%M:%S) capture queue complete" >&2
