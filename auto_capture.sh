#!/bin/bash
# Resumable on-chip capture queue for a flaky tunnel: each item is run
# DIRECTLY (no separate probe client — every item signals a wedged
# backend by exiting 4, costing exactly one client creation per
# attempt against the single-claim tunnel); on rc=4 sleep a long quiet
# gap and retry the same item (the tunnel has healed after 2-9h in
# past sessions, and client churn may itself hold the claim wedged).
# Items are ordered value-first/risk-last.  Item exit codes: 4 =
# wedged before any real work (do NOT advance — retry next window);
# 3 = internal watchdog fired mid work (advance; the item is suspect
# and gets a diagnostic JSON line).
set -u
cd "$(dirname "$0")"
CURSOR_FILE="${CAPTURE_CURSOR:-.capture_cursor}"
LOG=measurements.jsonl
# The cursor is POSITIONAL; a queue hash stored next to it makes that
# self-enforcing — any non-append edit resets the resume point.

QUEUE=(
  # headline configs FIRST (one client creation each — the claim-churn
  # lesson): re-measured on the shape-aware flash dispatch (the round-3
  # numbers in BENCH_HISTORY predate it: seq-128 attention now takes
  # the XLA path, which the kernel A/B measured 1.2x faster there)
  "timeout 700 python bench.py --no-kernels"
  "timeout 700 python bench.py --bert --no-kernels"
  "timeout 700 python bench.py --gpt --no-kernels"
  # diagnose prints human progress lines to stdout: route them to its own
  # log so the measurements JSONL stream stays parseable (its JSON result
  # lines go to diagnose_gpt1024.jsonl via DIAG_LOG); it probes between
  # stages (several client creations) so it runs after the headlines
  "bash diagnose_gpt1024.sh >>diagnose_stdout.log 2>&1"
  "timeout 700 python bench.py --profile"
  "timeout 700 python bench.py --profile --gpt"
  "timeout 900 python bench.py --sweep 96,128,192,256 --no-kernels --budget-s 840"
  "timeout 900 python bench.py --gpt --sweep 32,64,128 --no-kernels --budget-s 840"
  "timeout 700 python bench.py --llama --no-kernels"
  "timeout 700 python bench.py --gpt-decode --no-kernels"
  "timeout 700 python bench.py --gpt-decode --int8 --no-kernels"
  "timeout 900 python bench.py --spec-decode --no-kernels --budget-s 840"
  "timeout 700 python bench.py --gpt-decode --int8 --kv-int8 --no-kernels"
  "timeout 700 python bench.py --seq2seq --no-kernels"
  "timeout 900 python bench.py --kernels-timing --budget-s 840"
  # intermediate long-seq datapoint (flash engages at 512 under the
  # new dispatch; lower-risk than the seq-1024 config that hung)
  "timeout 700 python bench.py 32 --gpt --seq-len 512 --no-kernels"
  "timeout 700 python bench.py --llama --seq-len 512 --no-kernels"
  "timeout 700 python bench.py --vit --no-kernels"
  "timeout 700 python bench.py --dcgan --no-kernels"
  "timeout 700 python bench.py --profile --llama"
  "DIAG_FULL=1 bash diagnose_gpt1024.sh >>diagnose_stdout.log 2>&1"
  # channels-last A/B arm (appended round 4: nn.to_channels_last) — the
  # conv-layout lever against the 0.28-MFU NCHW headline, plus its
  # profile attribution
  "timeout 700 python bench.py --nhwc --no-kernels"
  "timeout 700 python bench.py --profile --nhwc"
  # llama GQA decode ladder + the rolling-cache A/B (window arm reads
  # O(window) cache per token instead of O(context) — sized so the KV
  # term is visible against the 125M weights: B=16, 512-token prompt)
  "timeout 700 python bench.py --llama-decode --no-kernels"
  "timeout 700 python bench.py 16 --llama-decode --seq-len 512 --no-kernels"
  "timeout 700 python bench.py 16 --llama-decode --seq-len 512 --window 128 --no-kernels"
  # appended round-4 continuation: the seq-1024 configs the xentropy OOM
  # crash blocked (diagnose round 4: flash/rbg clean, xentropy at
  # (16384, 50257) died) — re-measured on the row-blocked xentropy
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --no-kernels"
  "timeout 700 python bench.py 16 --llama --seq-len 1024 --no-kernels"
  # GPT sweep re-run: the 08:45 UTC capture hit a shared-tunnel
  # contention window (uniform 1.6x slowdown incl. compiles; llama at
  # 08:48 healthy) — its points contradict the same-config headline
  "timeout 900 python bench.py --gpt --sweep 32,64,128 --no-kernels --budget-s 840"
  # spec-decode re-run on the teacher-forced exactness gate (the prefix
  # gate cascade-failed on a benign position-147 argmax tie at 08:52)
  "timeout 900 python bench.py --spec-decode --no-kernels --budget-s 840"
  # profile re-runs now that the unattributed bucket is split by thunk
  # category (the 08:38 resnet profile left 72% of step time unnamed)
  "timeout 700 python bench.py --profile"
  "timeout 700 python bench.py --profile --gpt"
  # seq-1024 "before" attribution (ran on pre-in-kernel-dropout code:
  # names the materializing XLA attention + mask-RNG cost that the
  # dropout kernel work below then removes)
  "timeout 700 python bench.py 16 --profile --gpt --seq-len 1024"
  # post-in-kernel-dropout re-measures: GPT/BERT attention now rides
  # flash (or the hash-masked XLA path at short seq) WITH dropout —
  # no (S, S) mask tensors, no rbg mask generation in the step.  The
  # second seq-1024 profile is the "after" arm of the one above.
  "timeout 700 python bench.py --gpt --no-kernels"
  "timeout 700 python bench.py --bert --no-kernels"
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --no-kernels"
  "timeout 700 python bench.py 16 --profile --gpt --seq-len 1024"
  "timeout 700 python bench.py 32 --bert --seq-len 512 --no-kernels"
  "timeout 700 python bench.py --seq2seq --no-kernels"
  # re-measures after replacing the xentropy backward's scatter with a
  # fused iota-compare (the scatter was the 1.6x seq-128 LM regression
  # first seen in the 08:45 sweep)
  "timeout 700 python bench.py --gpt --no-kernels"
  "timeout 700 python bench.py --bert --no-kernels"
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --no-kernels"
  # in-kernel attention dropout arms (the historical GPT-2/BERT recipes
  # the stable headline configs omit) + the acceptance-logged spec run
  "timeout 700 python bench.py --gpt --attn-dropout 0.1 --no-kernels"
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --attn-dropout 0.1 --no-kernels"
  "timeout 700 python bench.py --bert --attn-dropout 0.1 --no-kernels"
  "timeout 900 python bench.py --spec-decode --no-kernels --budget-s 840"
  # post-scatter-fix seq-512 re-measures (the 08:55 rows carried the
  # regression) + the seq-2048 long-context flagship number.  (A
  # latency-hiding-scheduler arm ran here and died at init: the flag
  # does not exist in this XLA build — no scheduler knob to A/B.)
  "timeout 700 python bench.py 32 --gpt --seq-len 512 --no-kernels"
  "timeout 700 python bench.py --llama --seq-len 512 --no-kernels"
  "timeout 900 python bench.py 8 --llama --seq-len 2048 --no-kernels --budget-s 840"
  "timeout 700 env XLA_FLAGS=--xla_tpu_enable_latency_hiding_scheduler=true python bench.py --no-kernels"
  # resnet profile on BOTH umbrella filters (the committed batch-128
  # row predates the run-index filter: 52 ms of a 54 ms step sat in
  # 'other') — the recorded backing for docs/performance.md's table
  "timeout 700 python bench.py --profile"
  # clean LM profiles: the 09:52 gpt row showed an ~8.6 ms/exec 'while'
  # bucket (12% of the step) worth naming, and bert was never profiled
  "timeout 700 python bench.py --profile --gpt"
  "timeout 700 python bench.py --profile --bert"
  # Pallas xentropy kernel landed (block-local casts vs ~14 ms/step of
  # materialized f32 conversions in the jnp path): kernel A/B rows at
  # the LM loss shapes + headline re-measures on the kernel path
  "timeout 900 python bench.py --kernels-timing --budget-s 840"
  "timeout 700 python bench.py --gpt --no-kernels"
  "timeout 700 python bench.py --bert --no-kernels"
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --no-kernels"
  "timeout 700 python bench.py --seq2seq --no-kernels"
  "timeout 700 python bench.py --profile --gpt"
  # the xentropy kernel A/B came back 0.38x/0.74x (it LOSES to XLA's
  # fusion; VPU-bound block sweep) — kernel now gated off by default;
  # these re-measures confirm the headlines restored on the jnp path
  "timeout 700 python bench.py --gpt --no-kernels"
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --no-kernels"
  # lane-padded vocab A/B (Megatron make-vocab-size-divisible-by:
  # 50257 -> 50304): does aligning the head matmul move the headline?
  "timeout 700 python bench.py --gpt --pad-vocab --no-kernels"
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --pad-vocab --no-kernels"
  # FINAL-CODE confirmation sweep (suite 684 green): every headline and
  # the kernel table once more on the round's last commit, so
  # BENCH_HISTORY's closing numbers and BENCH_r04 share one code state
  "timeout 700 python bench.py --no-kernels"
  "timeout 700 python bench.py --bert --no-kernels"
  "timeout 700 python bench.py --gpt --no-kernels"
  "timeout 700 python bench.py --llama --no-kernels"
  "timeout 700 python bench.py 16 --gpt --seq-len 1024 --no-kernels"
  "timeout 900 python bench.py --kernels-timing --budget-s 840"
  # llama long-seq refresh: the 87-seq/s llama-1024 row (09:42) carried
  # the scatter-era xentropy like the 1027 headline did (final clean
  # headline: 1359.5) — one clean long-seq llama number to close on
  "timeout 700 python bench.py 16 --llama --seq-len 1024 --no-kernels"
)

# No separate probe client: bench.py itself exits 4 when the backend
# is wedged at init, so each attempt costs exactly ONE client creation
# against the single-claim tunnel (round-4 observation: the tunnel was
# healthy at 01:36, wedged for every probe from 01:38 on — the 10-min
# probe churn may itself hold the claim wedged; long quiet gaps give
# any leaked claim time to expire).  RETRY_SLEEP overridable for tests.
# cursor validity guard: positions only resume against the same queue
# PREFIX they were written for (appending is safe; any other edit
# resets to 0 rather than silently skipping/repeating items)
cursor=$(cat "$CURSOR_FILE" 2>/dev/null || echo 0)
if [ "$cursor" -gt 0 ]; then
  done_hash=$(printf '%s\n' "${QUEUE[@]:0:$cursor}" | sha256sum | cut -d' ' -f1)
  saved=$(cat "$CURSOR_FILE.qhash" 2>/dev/null || echo none)
  if [ "$saved" != "$done_hash" ]; then
    echo "$(date -u +%H:%M:%S) queue edited under a saved cursor; resetting to 0" >&2
    cursor=0
  fi
fi
while [ "$cursor" -lt "${#QUEUE[@]}" ]; do
  cmd="${QUEUE[$cursor]}"
  echo "$(date -u +%H:%M:%S) === item $cursor attempt: $cmd ===" >&2
  eval "$cmd" >>"$LOG" 2>>"$LOG.err"
  rc=$?
  if [ "$rc" -eq 4 ]; then
    echo "$(date -u +%H:%M:%S) item $cursor wedged at init (rc=4); quiet ${RETRY_SLEEP:-2400}s then retry" >&2
    sleep "${RETRY_SLEEP:-2400}"
    continue
  fi
  echo "$(date -u +%H:%M:%S) item $cursor done rc=$rc" >&2
  cursor=$((cursor + 1))
  echo "$cursor" >"$CURSOR_FILE"
  printf '%s\n' "${QUEUE[@]:0:$cursor}" | sha256sum | cut -d' ' -f1 \
    >"$CURSOR_FILE.qhash"
done
echo "$(date -u +%H:%M:%S) capture queue complete" >&2
