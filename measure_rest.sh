#!/bin/bash
# Remaining measurement matrix after the first healthy-tunnel window of
# round 3 (which captured resnet/bert/gpt-128 before the gpt seq-1024
# warmup hang re-wedged the tunnel).  Ordered low-risk-first so a single
# wedge cannot block the whole matrix; the risky long-sequence configs
# run LAST, with an automatic A/B bisect (threefry dropout / plain loss)
# if seq-1024 hangs again, to identify which round-3 change (if any) is
# responsible vs. plain tunnel flakiness.
set -u
LOG="${MEASURE_LOG:-measurements.jsonl}"
cd "$(dirname "$0")"

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((64, 64)); print('probe ok:', float(jnp.sum(x @ x)))
" 2>/dev/null
}

if ! probe; then
  echo "tunnel not healthy; aborting" >&2
  exit 1
fi

run() {
  echo "=== $* ===" >&2
  timeout 700 env "${ENVV[@]:-IGNORE=1}" python bench.py "$@" \
    2>>"$LOG.err" | tee -a "$LOG"
}

# value (not null) present in the LAST line of the log?
last_ok() {
  tail -1 "$LOG" | grep -q '"value": [0-9]'
}

ENVV=()
run --gpt-decode
probe || exit 1
run --seq2seq
probe || exit 1
run --kernels-timing
probe || exit 1
run --profile
probe || exit 1
run --profile --gpt
probe || exit 1
run --sweep 96,128,192,256
probe || exit 1
run --gpt --sweep 32,64,128
probe || exit 1

# ---- risky: long-sequence configs ----
run 16 --gpt --seq-len 1024
if last_ok; then
  probe || exit 1
  run 8 --gpt --seq-len 2048 --remat
  echo "done (full)" >&2
  exit 0
fi

# seq-1024 failed: bisect.  Each variant needs a healthy tunnel first.
echo "seq-1024 failed; bisecting (waiting for tunnel between variants)" >&2
wait_healthy() {
  local n=0
  until probe; do
    n=$((n + 1)); [ "$n" -gt 60 ] && return 1   # give up after ~5h
    sleep 240
  done
}

wait_healthy || exit 1
ENVV=(APEX_TPU_DROPOUT_IMPL=threefry)
run 16 --gpt --seq-len 1024          # variant A: threefry dropout
ENVV=()
last_a=$(tail -1 "$LOG")

wait_healthy || exit 1
run 16 --gpt --seq-len 1024 --plain-loss   # variant B: plain loss path
echo "bisect done: threefry=[$last_a] plain-loss=[$(tail -1 "$LOG")]" >&2
