#!/bin/bash
# Remaining measurement matrix after the first healthy-tunnel window of
# round 3 (which captured resnet/bert/gpt-128 before the gpt seq-1024
# warmup hang re-wedged the tunnel).  Ordered low-risk-first so a single
# wedge cannot block the whole matrix; the risky long-sequence configs
# run LAST, with an automatic A/B bisect (threefry dropout / plain loss)
# if seq-1024 hangs again, to identify which round-3 change (if any) is
# responsible vs. plain tunnel flakiness.
set -u
LOG="${MEASURE_LOG:-measurements.jsonl}"
cd "$(dirname "$0")"

if ! ./probe_tunnel.sh; then
  echo "tunnel not healthy; aborting" >&2
  exit 1
fi

run() {
  echo "=== $* ===" >&2
  timeout 700 env "${ENVV[@]:-IGNORE=1}" python bench.py "$@" \
    2>>"$LOG.err" | tee -a "$LOG"
}

# Did the MOST RECENT run() emit a fresh non-null JSON line?  A hung run
# is killed before it writes anything, so judging by the log's last line
# alone would credit it with the PREVIOUS config's success — count lines
# before/after instead.
lines() { [ -f "$LOG" ] && wc -l < "$LOG" || echo 0; }
run_ok() {  # usage: pre=$(lines); run ...; run_ok "$pre"
  [ "$(lines)" -gt "$1" ] && tail -1 "$LOG" | grep -q '"value": [0-9]'
}

ENVV=()
run --gpt-decode
./probe_tunnel.sh || exit 1
run --llama --seq-len 512 --iters 30
./probe_tunnel.sh || exit 1
run --seq2seq
./probe_tunnel.sh || exit 1
run --kernels-timing
./probe_tunnel.sh || exit 1
run --profile
./probe_tunnel.sh || exit 1
run --profile --gpt
./probe_tunnel.sh || exit 1
run --sweep 96,128,192,256
./probe_tunnel.sh || exit 1
run --gpt --sweep 32,64,128
./probe_tunnel.sh || exit 1

# ---- risky: long-sequence configs ----
pre=$(lines)
run 16 --gpt --seq-len 1024
if run_ok "$pre"; then
  ./probe_tunnel.sh || exit 1
  run 8 --gpt --seq-len 2048 --remat
  echo "done (full)" >&2
  exit 0
fi

# seq-1024 failed: bisect.  Each variant needs a healthy tunnel first
# (wait up to ~4h per variant — wedges have lasted hours).
echo "seq-1024 failed; bisecting (waiting for tunnel between variants)" >&2
./probe_tunnel.sh -w 60 || exit 1
ENVV=(APEX_TPU_DROPOUT_IMPL=threefry)
pre=$(lines)
run 16 --gpt --seq-len 1024          # variant A: threefry dropout
a_ok=$(run_ok "$pre" && echo yes || echo no)
ENVV=()

./probe_tunnel.sh -w 60 || exit 1
pre=$(lines)
run 16 --gpt --seq-len 1024 --plain-loss   # variant B: plain loss path
b_ok=$(run_ok "$pre" && echo yes || echo no)
echo "bisect done: threefry_ok=$a_ok plain_loss_ok=$b_ok" >&2
