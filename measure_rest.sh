#!/bin/bash
# Round-5 remaining captures: everything measure_all.sh had not yet
# drained when the tunnel wedged mid-sweep (plus the two items that
# failed under host-load starvation and the new BERT/seq2seq arms).
# Waits for a healthy tunnel first; appends to measurements.jsonl.
set -u
LOG="${MEASURE_LOG:-measurements.jsonl}"
cd "$(dirname "$0")"

bash probe_tunnel.sh -w || exit 1

run() {
  echo "=== $* ===" >&2
  timeout 1700 python bench.py "$@" 2>>"$LOG.err" | tee -a "$LOG"
}

run --bert                            # gathered-MLM default (NEW)
run --bert --full-mlm-head --no-kernels   # all-positions A/B arm
run --seq2seq                         # chunked vocab-chain default (NEW)
run --seq2seq --loss-mode fused --no-kernels
run 16 --gpt --seq-len 1024           # failed under host-load starvation
run 8 --gpt --seq-len 2048 --remat    # failed: tunnel wedge
run --gpt --loss-mode fused --no-kernels    # vocab-chain A/B anchor arm
run --kernels-timing --budget-s 1600  # variance-controlled + MLP row
run --gpt-decode
run --gpt-decode --int8
run --gpt-decode --int8 --kv-int8
run --llama-decode
run 16 --llama-decode --seq-len 512
run 16 --llama-decode --seq-len 512 --window 128
run --spec-decode --budget-s 1200     # trained draft (NEW)
run --spec-decode --draft-steps 60 --budget-s 1200  # low-acceptance point
run --spec-decode --draft random --no-kernels  # overhead-floor arm
run --dcgan
run --profile                         # resnet per-op attribution
run --profile --gpt                   # current-default (chunked) profile
run 32 --profile --vit
run --sweep 96,128,192,256            # resnet batch/MFU sweet spot
run --gpt --sweep 32,64,128           # gpt batch/MFU sweet spot
echo "done; results in $LOG" >&2
