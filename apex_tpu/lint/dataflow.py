"""Interprocedural abstract interpretation over the lint call graph.

The syntactic rules can see a hazard only where it is spelled: a
``.item()`` is flagged wherever it appears in traced-reachable code,
even on a value that provably lives on host; a traced value laundered
through two helper frames into ``static_argnames`` is invisible.  This
module closes that gap with a small abstract interpreter over the
:class:`~apex_tpu.lint.callgraph.CallGraph`: every function gets a
flow-insensitive abstract environment mapping names to
:class:`AbsVal` — a product of two finite lattices plus one flag —
computed to a fixpoint across calls, returns and closures.

**Taint lattice** (where does the value live under tracing?)::

            TOP            (conflicting evidence)
         /   |   \\
     HOST STATIC TRACED    (host python / trace-time static / tracer)
         \\   |   /
          UNKNOWN          (no evidence)

``TRACED`` seeds from a jit entry's own non-static parameters (the
call graph's provably-traced set) and from jax/jnp/lax constructor
results; ``HOST`` from python constants, ``float()``/``int()``,
``.item()``, ``jax.device_get`` and numpy results; ``STATIC`` from
``.shape``/``.dtype``/``len()`` reads and ``bucket*`` helpers.
Arithmetic *combines* (a tracer infects the expression); control-flow
merge *joins* (conflicts go to ``TOP``, which no rule trusts in either
direction).

**Dtype lattice**: ``UNKNOWN`` / ``WEAK`` (python scalar — jax's
weak-typed constants are dtype-transparent) / ``I8`` / ``F16`` /
``BF16`` / ``F32`` / ``OTHER``, with jnp's promotion for arithmetic
(``f16 + bf16 -> f32``, weak scalars preserve the array dtype).  A
dtype is only ever *definite*: an ``astype`` with a variable target
yields ``UNKNOWN``, so PRECISION-SINK flags proofs, not guesses.

**shape_derived**: True for values computed from a traced value's
``.shape``/``.size``/``len()`` — the program-identity surface
SHAPE-BRANCH polices.  Routing through any ``bucket*`` helper clears
it (the sanctioned O(log) quantization, same convention as
SERVE-SHAPE).

Interprocedural propagation: call-site argument values join into the
callee's parameter seeds (never into jit *entries* — their parameters
are pinned TRACED no matter what eager code passes), return values
summarize back to call sites, and nested defs read the enclosing
frame's environment.  Everything is monotone over finite lattices, so
the worklist terminates; ``max_visits`` is a safety bound only.
"""
from __future__ import annotations

import ast
import dataclasses
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

# -- taint lattice ----------------------------------------------------------

UNKNOWN, HOST, STATIC, TRACED, TOP = 0, 1, 2, 3, 4

_TAINT_NAMES = {UNKNOWN: "unknown", HOST: "host", STATIC: "static",
                TRACED: "traced", TOP: "top"}


def join_taint(a: int, b: int) -> int:
    """Control-flow merge: conflicting evidence goes to TOP."""
    if a == b:
        return a
    if a == UNKNOWN:
        return b
    if b == UNKNOWN:
        return a
    return TOP


def combine_taint(a: int, b: int) -> int:
    """Arithmetic/containment: a tracer infects the expression (a
    traced operand makes the result traced; TOP stays poisoned; a
    host+static mix is host python arithmetic)."""
    if TRACED in (a, b):
        return TRACED
    if TOP in (a, b):
        return TOP
    if HOST in (a, b):
        return HOST
    if STATIC in (a, b):
        return STATIC
    return UNKNOWN


# -- dtype lattice ----------------------------------------------------------

DT_UNKNOWN, DT_WEAK, DT_I8, DT_F16, DT_BF16, DT_F32, DT_OTHER = range(7)

_DTYPE_BY_NAME = {
    "float16": DT_F16, "half": DT_F16,
    "bfloat16": DT_BF16,
    "float32": DT_F32, "single": DT_F32, "float_": DT_F32,
    "int8": DT_I8,
    "float64": DT_OTHER, "double": DT_OTHER, "int32": DT_OTHER,
    "int64": DT_OTHER, "uint32": DT_OTHER, "bool_": DT_OTHER,
}

HALF_DTYPES = (DT_F16, DT_BF16)


def join_dtype(a: int, b: int) -> int:
    if a == b:
        return a
    if a in (DT_UNKNOWN, DT_WEAK):
        return b if a == DT_WEAK else DT_UNKNOWN
    if b in (DT_UNKNOWN, DT_WEAK):
        return a if b == DT_WEAK else DT_UNKNOWN
    return DT_UNKNOWN


def promote_dtype(a: int, b: int) -> int:
    """jnp-style result dtype of arithmetic: weak python scalars are
    transparent, f16+bf16 promotes to f32, i8 promotes into floats; any
    unknown operand makes the result unknown (never guess a half)."""
    if a == DT_WEAK:
        return b
    if b == DT_WEAK:
        return a
    if DT_UNKNOWN in (a, b) or DT_OTHER in (a, b):
        return DT_UNKNOWN if DT_UNKNOWN in (a, b) else DT_OTHER
    if a == b:
        return a
    if DT_F32 in (a, b):
        return DT_F32
    if {a, b} == {DT_F16, DT_BF16}:
        return DT_F32
    if DT_I8 in (a, b):
        return a if b == DT_I8 else b
    return DT_UNKNOWN


def dtype_const(node: ast.AST) -> int:
    """The definite dtype a ``dtype=`` argument / ``astype`` target
    names (``jnp.float16`` / ``np.float16`` / ``"float16"``), else
    UNKNOWN."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_BY_NAME.get(node.value, DT_UNKNOWN)
    if isinstance(node, ast.Attribute):
        return _DTYPE_BY_NAME.get(node.attr, DT_UNKNOWN)
    if isinstance(node, ast.Name):
        return _DTYPE_BY_NAME.get(node.id, DT_UNKNOWN)
    return DT_UNKNOWN


# -- abstract values --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AbsVal:
    """One abstract value: taint x dtype x shape-derived flag."""
    taint: int = UNKNOWN
    dtype: int = DT_UNKNOWN
    shape_derived: bool = False

    def __repr__(self):
        bits = [_TAINT_NAMES[self.taint]]
        if self.dtype != DT_UNKNOWN:
            bits.append(f"dt{self.dtype}")
        if self.shape_derived:
            bits.append("shape")
        return f"<{' '.join(bits)}>"

    @property
    def is_traced(self) -> bool:
        return self.taint == TRACED

    @property
    def is_host(self) -> bool:
        return self.taint == HOST

    @property
    def is_half(self) -> bool:
        return self.dtype in HALF_DTYPES


BOTTOM = AbsVal()


def join(a: AbsVal, b: AbsVal) -> AbsVal:
    return AbsVal(join_taint(a.taint, b.taint),
                  join_dtype(a.dtype, b.dtype),
                  a.shape_derived or b.shape_derived)


def combine(a: AbsVal, b: AbsVal) -> AbsVal:
    return AbsVal(combine_taint(a.taint, b.taint),
                  promote_dtype(a.dtype, b.dtype),
                  a.shape_derived or b.shape_derived)


#: array methods whose result keeps the receiver's taint and (absent a
#: dtype= override) its dtype
_ARRAY_METHODS = {
    "sum", "mean", "max", "min", "prod", "cumsum", "cumprod", "dot",
    "matmul", "reshape", "transpose", "swapaxes", "squeeze", "ravel",
    "flatten", "copy", "conj", "clip", "round", "take", "repeat",
    "at", "set", "add", "get", "block_until_ready", "std", "var",
}

#: methods that fetch to host
_HOST_METHODS = {"item", "tolist", "to_py"}

_HOST_BUILTINS = {"float", "int", "bool", "str", "repr", "format",
                  "hash", "print"}

#: builtins transparent to taint/shape_derived (min(n, cap) of a
#: shape-derived extent is still shape-derived; bucket* is the one
#: sanctioned quantizer)
_PASSTHRU_BUILTINS = {"min", "max", "abs", "sum", "sorted", "list",
                      "tuple", "set", "dict", "zip", "enumerate",
                      "range", "reversed", "round", "divmod", "getattr"}

#: external roots classified wholesale
_HOST_ROOTS = ("numpy", "math", "os", "time", "random", "itertools",
               "functools.reduce")


@dataclasses.dataclass
class FunctionFacts:
    """Fixpoint result for one function."""
    params: Dict[str, AbsVal]           # parameter seeds (joined)
    env: Dict[str, AbsVal]              # final flow-insensitive env
    ret: AbsVal                         # return summary


class _State:
    """Per-analysis evaluation state: the local env plus the closure
    lookup chain."""
    __slots__ = ("df", "info", "env", "ret")

    def __init__(self, df, info, env):
        self.df = df
        self.info = info
        self.env = env
        self.ret = BOTTOM

    def lookup(self, name: str) -> AbsVal:
        v = self.env.get(name)
        if v is not None:
            return v
        # closure chain: nested defs read the enclosing frame's env
        path, parent = self.info.module_path, self.info.parent
        seen = 0
        while parent is not None and seen < 8:
            pf = self.df.facts.get((path, parent))
            if pf is not None and name in pf.env:
                return pf.env[name]
            fi = self.df.cg.functions.get((path, parent))
            parent = fi.parent if fi is not None else None
            seen += 1
        return BOTTOM


class Dataflow:
    """The fixpoint engine plus the query API the rules use.

    ``facts`` maps ``(module_path, qualname)`` to
    :class:`FunctionFacts`; :meth:`eval_in` re-evaluates an arbitrary
    expression under a function's final environment (joins are
    saturated at fixpoint, so re-evaluation is side-effect-free in the
    lattice sense).
    """

    def __init__(self, modules, callgraph, max_visits: int = 10):
        self.cg = callgraph
        self.max_visits = max_visits
        self.facts: Dict[Tuple[str, str], FunctionFacts] = {}
        self._module_globals: Dict[str, Set[str]] = {}
        for m in modules:
            self._module_globals[m.path] = self._collect_globals(m)
        for key, info in self.cg.functions.items():
            self.facts[key] = FunctionFacts(
                params=self._seed_params(key, info), env={}, ret=BOTTOM)
        self._callers: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for key, info in self.cg.functions.items():
            for callee in self.cg._callees(info):
                self._callers.setdefault(callee, set()).add(key)
        self._run_fixpoint()

    # -- setup -------------------------------------------------------------

    @staticmethod
    def _collect_globals(module) -> Set[str]:
        out: Set[str] = set()
        for node in module.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    @staticmethod
    def _defaulted_params(info) -> Set[str]:
        """Parameters bound to a default at the def site.  On a traced
        entry these are almost never passed by the tracer — jax closes
        over the default as a trace-time constant (the ``prog=program``
        idiom) — so they must not seed TRACED."""
        args = getattr(info.node, "args", None)
        if args is None:
            return set()
        out: Set[str] = set()
        pos = list(getattr(args, "posonlyargs", ())) + list(args.args)
        for a, d in zip(reversed(pos), reversed(args.defaults)):
            if d is not None:
                out.add(a.arg)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                out.add(a.arg)
        return out

    def _seed_params(self, key, info) -> Dict[str, AbsVal]:
        seeds: Dict[str, AbsVal] = {}
        if key in self.cg._entries:
            static = self.cg._entry_static.get(key, set())
            defaulted = self._defaulted_params(info)
            for p in info.params:
                if p in defaulted:
                    continue
                seeds[p] = AbsVal(STATIC) if p in static \
                    else AbsVal(TRACED)
        return seeds

    # -- fixpoint ----------------------------------------------------------

    def _run_fixpoint(self):
        order = sorted(self.cg.functions)
        queue = deque(order)
        queued = set(order)
        visits: Dict[Tuple[str, str], int] = {}

        def enqueue(k):
            if k in self.cg.functions and k not in queued:
                queue.append(k)
                queued.add(k)

        self._enqueue = enqueue
        while queue:
            key = queue.popleft()
            queued.discard(key)
            n = visits.get(key, 0)
            if n >= self.max_visits:
                continue
            visits[key] = n + 1
            if self._analyze(key):
                for caller in self._callers.get(key, ()):
                    enqueue(caller)
                for child in self.cg._children.get(key, ()):
                    enqueue(child)
        self._enqueue = lambda k: None      # queries must not requeue

    def _analyze(self, key) -> bool:
        info = self.cg.functions[key]
        facts = self.facts[key]
        env = dict(facts.params)
        st = _State(self, info, env)
        # two passes make the flow-insensitive env closed under
        # use-before-def within one body (joins are monotone)
        for _ in range(2):
            self._exec_block(info.node.body, st)
        changed = (env != facts.env) or (st.ret != facts.ret)
        facts.env = env
        facts.ret = st.ret
        return changed

    # -- statements --------------------------------------------------------

    def _exec_block(self, stmts, st):
        for s in stmts:
            self._exec_stmt(s, st)

    def _exec_stmt(self, s, st):
        if isinstance(s, ast.Assign):
            self._bind(s.targets, s.value, st)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._bind([s.target], s.value, st)
        elif isinstance(s, ast.AugAssign):
            v = combine(self.eval(ast.Name(id=s.target.id,
                                           ctx=ast.Load())
                                  if isinstance(s.target, ast.Name)
                                  else s.target, st),
                        self.eval(s.value, st)) \
                if isinstance(s.target, ast.Name) \
                else self.eval(s.value, st)
            if isinstance(s.target, ast.Name):
                st.env[s.target.id] = join(
                    st.env.get(s.target.id, BOTTOM), v)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                st.ret = join(st.ret, self.eval(s.value, st))
        elif isinstance(s, (ast.If, ast.While)):
            self.eval(s.test, st)
            self._exec_block(s.body, st)
            self._exec_block(s.orelse, st)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            it = self.eval(s.iter, st)
            self._bind_value(s.target,
                             AbsVal(it.taint, it.dtype,
                                    it.shape_derived), st)
            self._exec_block(s.body, st)
            self._exec_block(s.orelse, st)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                v = self.eval(item.context_expr, st)
                if item.optional_vars is not None:
                    self._bind_value(item.optional_vars, v, st)
            self._exec_block(s.body, st)
        elif isinstance(s, ast.Try):
            self._exec_block(s.body, st)
            for h in s.handlers:
                self._exec_block(h.body, st)
            self._exec_block(s.orelse, st)
            self._exec_block(s.finalbody, st)
        elif isinstance(s, ast.Expr):
            self.eval(s.value, st)
        # nested FunctionDef/ClassDef: analyzed as their own functions

    def _bind(self, targets, value, st):
        # tuple-to-tuple assignments bind elementwise so `a, b = f(x), 3`
        # does not smear f's taint onto b
        for tgt in targets:
            if isinstance(tgt, (ast.Tuple, ast.List)) and \
                    isinstance(value, (ast.Tuple, ast.List)) and \
                    len(tgt.elts) == len(value.elts):
                for t, v in zip(tgt.elts, value.elts):
                    self._bind([t], v, st)
                return
        v = self.eval(value, st)
        for tgt in targets:
            self._bind_value(tgt, v, st)

    def _bind_value(self, tgt, v: AbsVal, st):
        if isinstance(tgt, ast.Name):
            # a FIRST bind overwrites (BOTTOM means "no evidence yet",
            # not "evidence of unknown" — joining would erase a definite
            # dtype); later rebinds join, staying flow-insensitive
            cur = st.env.get(tgt.id, BOTTOM)
            st.env[tgt.id] = v if cur == BOTTOM else join(cur, v)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind_value(el, v, st)
        elif isinstance(tgt, ast.Starred):
            self._bind_value(tgt.value, v, st)
        # Attribute/Subscript stores carry no env binding (TRACER-LEAK
        # inspects them directly)

    # -- expressions -------------------------------------------------------

    def eval(self, node, st: _State) -> AbsVal:
        if node is None:
            return BOTTOM
        if isinstance(node, ast.Constant):
            dt = DT_WEAK if isinstance(node.value, (int, float, bool)) \
                else DT_UNKNOWN
            return AbsVal(HOST, dt, False)
        if isinstance(node, ast.Name):
            return st.lookup(node.id)
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node, st)
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value, st)
            self.eval(node.slice, st)
            return base
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = BOTTOM
            for el in node.elts:
                out = combine(out, self.eval(el, st))
            return out
        if isinstance(node, ast.Dict):
            out = BOTTOM
            for k, v in zip(node.keys, node.values):
                if k is not None:
                    self.eval(k, st)
                out = combine(out, self.eval(v, st))
            return out
        if isinstance(node, ast.BinOp):
            return combine(self.eval(node.left, st),
                           self.eval(node.right, st))
        if isinstance(node, ast.BoolOp):
            out = BOTTOM
            for v in node.values:
                out = combine(out, self.eval(v, st))
            return out
        if isinstance(node, ast.Compare):
            out = self.eval(node.left, st)
            for c in node.comparators:
                out = combine(out, self.eval(c, st))
            # comparisons yield bools; keep taint + shape_derived only
            return AbsVal(out.taint, DT_UNKNOWN, out.shape_derived)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, st)
        if isinstance(node, ast.IfExp):
            self.eval(node.test, st)
            return join(self.eval(node.body, st),
                        self.eval(node.orelse, st))
        if isinstance(node, ast.Call):
            return self._eval_call(node, st)
        if isinstance(node, ast.Lambda):
            return BOTTOM
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            for child in ast.iter_child_nodes(node):
                self.eval(child, st)
            return AbsVal(HOST)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, st)
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                it = self.eval(gen.iter, st)
                self._bind_value(gen.target,
                                 AbsVal(it.taint, it.dtype,
                                        it.shape_derived), st)
                for cond in gen.ifs:
                    self.eval(cond, st)
            if isinstance(node, ast.DictComp):
                self.eval(node.key, st)
                return self.eval(node.value, st)
            return self.eval(node.elt, st)
        if isinstance(node, (ast.Slice,)):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self.eval(part, st)
            return BOTTOM
        if isinstance(node, ast.NamedExpr):
            v = self.eval(node.value, st)
            self._bind_value(node.target, v, st)
            return v
        if isinstance(node, ast.Await):
            return self.eval(node.value, st)
        return BOTTOM

    def _eval_attr(self, node: ast.Attribute, st) -> AbsVal:
        attr = node.attr
        base = self.eval(node.value, st)
        if attr in ("shape", "size"):
            return AbsVal(STATIC, DT_UNKNOWN,
                          base.taint == TRACED or base.shape_derived)
        if attr in ("dtype", "ndim", "sharding", "device"):
            # rank/dtype/placement are static and BOUNDED — branching on
            # them is specialization, not traffic-driven retrace
            return AbsVal(STATIC, DT_UNKNOWN, False)
        if attr in ("T", "mT", "real", "imag", "data", "grad", "value"):
            return base
        if self._external_root(node, st) is not None:
            # module attribute (jnp.float16, math.pi, ...) — a dtype
            # token or host constant, not an array
            return AbsVal(HOST)
        # attribute of a traced container (state.params) is traced;
        # other taints don't survive a field read we know nothing about
        t = base.taint if base.taint in (TRACED, HOST, STATIC) \
            else UNKNOWN
        return AbsVal(t, DT_UNKNOWN, False)

    def _external_root(self, node, st) -> Optional[str]:
        """The external dotted module a Name/Attribute chain is rooted
        at (``jnp.zeros`` -> "jax.numpy"), else None."""
        while isinstance(node, ast.Attribute):
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        table = self.cg.imports.get(st.info.module_path)
        if table is None:
            return None
        return table.ext_alias.get(node.id)

    # -- calls -------------------------------------------------------------

    def _eval_call(self, node: ast.Call, st) -> AbsVal:
        argvals = [self.eval(a, st) for a in node.args]
        kwvals = {kw.arg: self.eval(kw.value, st)
                  for kw in node.keywords}
        func = node.func
        tn = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)

        # the sanctioned shape quantizer: any bucket* helper
        if tn and "bucket" in tn:
            return AbsVal(STATIC)

        dt_kw = DT_UNKNOWN
        for kw in node.keywords:
            if kw.arg == "dtype":
                dt_kw = dtype_const(kw.value)

        if isinstance(func, ast.Name):
            v = self._eval_name_call(node, tn, argvals, dt_kw, st)
            if v is not None:
                return v
        elif isinstance(func, ast.Attribute):
            v = self._eval_attr_call(node, tn, argvals, dt_kw, st)
            if v is not None:
                return v
        elif isinstance(func, ast.Call):
            # jax.jit(f, ...)(x) / partial(f, ...)(x)
            self.eval(func, st)
            inner = func.func
            root = self._external_root(inner, st) or ""
            inner_tn = inner.attr if isinstance(inner, ast.Attribute) \
                else (inner.id if isinstance(inner, ast.Name) else "")
            if root.startswith("jax") or inner_tn in ("jit", "pjit"):
                return AbsVal(TRACED, dt_kw, False)
            return BOTTOM
        return BOTTOM

    def _eval_name_call(self, node, tn, argvals, dt_kw, st):
        a0 = argvals[0] if argvals else BOTTOM
        if tn in _HOST_BUILTINS:
            return AbsVal(HOST, DT_UNKNOWN, a0.shape_derived)
        if tn == "len":
            return AbsVal(STATIC, DT_UNKNOWN,
                          a0.taint == TRACED or a0.shape_derived)
        if tn in ("isinstance", "hasattr", "callable", "type", "id"):
            return AbsVal(HOST)
        if tn in _PASSTHRU_BUILTINS:
            out = BOTTOM
            for v in argvals:
                out = combine(out, v)
            return out
        # bare names imported from an external module
        root = self._external_root(node.func, st)
        if root is not None:
            return self._external_call(node, tn, root, argvals, dt_kw)
        # intra-package resolution
        callees = self._resolve_name_call(node.func.id, st)
        if callees:
            return self._summarize_call(node, callees, argvals, st)
        return None

    def _eval_attr_call(self, node, tn, argvals, dt_kw, st):
        func = node.func
        root = self._external_root(func, st)
        if root is not None:
            return self._external_call(node, tn, root, argvals, dt_kw)
        # module-alias resolution into the analyzed set: `mod.fn(...)`
        if isinstance(func.value, ast.Name):
            table = self.cg.imports.get(st.info.module_path)
            if table is not None and func.value.id in table.mod_alias:
                path = table.mod_alias[func.value.id]
                callees = [(path, qn) for qn in
                           self.cg.by_name.get(path, {}).get(tn, ())]
                if callees:
                    return self._summarize_call(node, callees, argvals,
                                                st)
                return None
        # method call on a value
        base = self.eval(func.value, st)
        if tn in _HOST_METHODS:
            return AbsVal(HOST, base.dtype, base.shape_derived)
        if tn == "astype":
            target = dtype_const(node.args[0]) if node.args else DT_UNKNOWN
            return AbsVal(base.taint, target, base.shape_derived)
        if tn in _ARRAY_METHODS:
            dt = dt_kw if dt_kw != DT_UNKNOWN else base.dtype
            return AbsVal(base.taint, dt, False)
        if base.taint == TRACED:
            # methods of traced pytrees (state._replace(...)) stay traced
            return AbsVal(TRACED, DT_UNKNOWN, False)
        return None

    def _external_call(self, node, tn, root, argvals, dt_kw):
        if root.startswith("jax"):
            if tn in ("device_get", "device_get_async"):
                a0 = argvals[0] if argvals else BOTTOM
                return AbsVal(HOST, a0.dtype, False)
            dt = dt_kw
            if dt == DT_UNKNOWN and tn not in ("zeros", "ones", "full",
                                               "empty", "arange"):
                # elementwise/reduction results promote operand dtypes
                for v in argvals:
                    dt = promote_dtype(dt, v.dtype) \
                        if dt != DT_UNKNOWN else v.dtype
                if any(v.dtype == DT_UNKNOWN for v in argvals):
                    dt = DT_UNKNOWN
            return AbsVal(TRACED, dt, False)
        if root.startswith(_HOST_ROOTS):
            return AbsVal(HOST, dt_kw, False)
        return None

    def _resolve_name_call(self, name: str, st):
        out: List[Tuple[str, str]] = []
        path = st.info.module_path
        for qn in self.cg.by_name.get(path, {}).get(name, ()):
            out.append((path, qn))
        table = self.cg.imports.get(path)
        if table is not None and name in table.func_alias:
            tpath, fn = table.func_alias[name]
            for qn in self.cg.by_name.get(tpath, {}).get(fn, ()):
                out.append((tpath, qn))
        return out

    def _summarize_call(self, node, callees, argvals, st) -> AbsVal:
        out = BOTTOM
        for key in callees:
            if key not in self.facts:
                continue
            self._seed_call_args(key, node, argvals, st)
            out = join(out, self.facts[key].ret)
        return out

    def _seed_call_args(self, callee_key, call, argvals, st):
        """Join this call site's argument values into the callee's
        parameter seeds (direct calls only; jit entries keep their
        pinned TRACED seeds — eager invocations of a jitted function
        pass host arrays that BECOME tracers)."""
        if callee_key in self.cg._entries:
            return
        cinfo = self.cg.functions[callee_key]
        cf = self.facts[callee_key]
        a = cinfo.node.args
        pos = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        if pos and pos[0] in ("self", "cls"):
            return          # unbound-method resolution would misalign
        changed = False

        def put(name, v):
            nonlocal changed
            nv = join(cf.params.get(name, BOTTOM), v)
            if nv != cf.params.get(name):
                cf.params[name] = nv
                changed = True

        for i, v in enumerate(argvals):
            if i < len(call.args) and \
                    isinstance(call.args[i], ast.Starred):
                break
            if i < len(pos):
                put(pos[i], v)
            elif a.vararg is not None:
                put(a.vararg.arg, v)
            else:
                break
        for kw in call.keywords:
            if kw.arg and kw.arg in cinfo.params:
                put(kw.arg, self.eval(kw.value, st))
        if changed:
            self._enqueue(callee_key)

    # -- query API ----------------------------------------------------------

    def facts_for(self, module_path: str,
                  qualname: str) -> Optional[FunctionFacts]:
        return self.facts.get((module_path, qualname))

    def eval_in(self, info, expr) -> AbsVal:
        """Evaluate ``expr`` under ``info``'s final environment (for
        rules; the fixpoint is saturated, so the extra joins this may
        perform are no-ops)."""
        facts = self.facts.get((info.module_path, info.qualname))
        env = dict(facts.env) if facts is not None else {}
        st = _State(self, info, env)
        return self.eval(expr, st)

    def module_globals(self, module_path: str) -> Set[str]:
        return self._module_globals.get(module_path, set())

    def functions_in(self, module_path: str):
        """Every FunctionInfo in a file, reachable or not (sorted for
        deterministic rule output)."""
        return [self.cg.functions[k] for k in sorted(self.cg.functions)
                if k[0] == module_path]


def build(modules, callgraph) -> Dataflow:
    return Dataflow(modules, callgraph)
