"""Lightweight intra-package call graph for reachability-scoped rules.

HOST-SYNC only fires *inside code that XLA traces*: a ``float(loss)`` in
an eager logging loop is normal, the same call inside a jitted train
step is a device round-trip per step.  Statically approximating "traced"
needs (a) the set of functions handed to jax's tracing entry points
(``jax.jit``/``pjit``/``shard_map``/``lax.scan``/``grad``/...), and
(b) the closure of intra-package calls from those — which this module
computes over whatever file set the engine was pointed at, resolving
bare-name calls within a module and ``alias.func`` calls through the
module's import table.  Deliberately conservative: unresolvable calls
(methods, higher-order parameters) are dropped rather than guessed, so
reachability under-approximates and the rule never flags eager code.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

#: callables whose function-valued argument(s) are traced by jax.
#: value = indices of the positional args that are functions.
_TRACERS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,), "pjit": (0,), "shard_map": (0,), "checkpoint": (0,),
    "remat": (0,), "grad": (0,), "value_and_grad": (0,), "vjp": (0,),
    "jvp": (0,), "custom_vjp": (0,), "vmap": (0,), "pmap": (0,),
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,), "map": (0,),
    "cond": (1, 2), "switch": (1, 2, 3, 4),
}

#: tracer names that are only jax tracers when spelled through jax.lax —
#: a bare/other-owner `map`/`cond`/`scan` (builtin map, jax.tree.map,
#: itertools chains) traces nothing
_LAX_ONLY = {"scan", "while_loop", "fori_loop", "map", "cond", "switch"}


def _terminal_name(func: ast.AST) -> Optional[str]:
    """`jax.jit` -> "jit", `lax.scan` -> "scan", `jit` -> "jit"."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _static_argnames_of(call: ast.Call):
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            return {e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return set()


@dataclasses.dataclass
class FunctionInfo:
    module_path: str
    qualname: str
    name: str
    node: ast.AST               # FunctionDef / AsyncFunctionDef
    params: Set[str]
    parent: Optional[str]       # enclosing function qualname, if nested


def _params_of(node) -> Set[str]:
    a = node.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return set(names)


class _ImportTable:
    """Per-module view of what names resolve to inside the analyzed set."""

    def __init__(self, module, dotted_to_path: Dict[str, str]):
        self.mod_alias: Dict[str, str] = {}    # local name -> module path
        self.func_alias: Dict[str, Tuple[str, str]] = {}  # -> (path, fn)
        self.ext_alias: Dict[str, str] = {}    # local name -> ext dotted
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    local = al.asname or al.name.split(".")[0]
                    target = al.name if al.asname else al.name.split(".")[0]
                    if target in dotted_to_path:
                        self.mod_alias[local] = dotted_to_path[target]
                    else:
                        self.ext_alias[local] = al.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # relative import: climb `level` packages from here
                    anchor = (module.dotted or "").split(".")
                    anchor = anchor[:max(0, len(anchor) - node.level)]
                    base = ".".join(anchor + ([base] if base else []))
                for al in node.names:
                    local = al.asname or al.name
                    sub = f"{base}.{al.name}" if base else al.name
                    if sub in dotted_to_path:
                        self.mod_alias[local] = dotted_to_path[sub]
                    elif base in dotted_to_path:
                        self.func_alias[local] = (dotted_to_path[base],
                                                  al.name)
                    else:
                        self.ext_alias[local] = sub


class CallGraph:
    """Functions, traced-entry set, and the reachable closure."""

    def __init__(self, modules):
        self.modules = {m.path: m for m in modules}
        dotted_to_path = {}
        for m in modules:
            if m.dotted:
                dotted_to_path[m.dotted] = m.path
        self.imports = {m.path: _ImportTable(m, dotted_to_path)
                        for m in modules}
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        self.by_name: Dict[str, Dict[str, List[str]]] = {}
        for m in modules:
            self._collect_functions(m)
        self._children: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        for key, fi in self.functions.items():
            if fi.parent:
                self._children.setdefault(
                    (fi.module_path, fi.parent), []).append(key)
        self._entries: Set[Tuple[str, str]] = set()
        self._entry_static: Dict[Tuple[str, str], Set[str]] = {}
        for m in modules:
            self._collect_entries(m)
        self.reachable = self._closure()

    # -- collection --------------------------------------------------------

    def _collect_functions(self, module):
        per_name = self.by_name.setdefault(module.path, {})

        def visit(node, prefix, parent):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    self.functions[(module.path, qn)] = FunctionInfo(
                        module.path, qn, child.name, child,
                        _params_of(child), parent)
                    per_name.setdefault(child.name, []).append(qn)
                    visit(child, qn + ".", qn)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.", parent)
                else:
                    visit(child, prefix, parent)

        visit(module.tree, "", None)

    def _fn_args_of_call(self, call: ast.Call, module_path=None):
        name = _terminal_name(call.func)
        if name not in _TRACERS:
            return []
        if name in _LAX_ONLY:
            # require the jax.lax spelling: `lax.scan` / `jax.lax.scan`,
            # or a bare name imported from jax.lax
            func = call.func
            if isinstance(func, ast.Attribute):
                owner = func.value
                ok = (isinstance(owner, ast.Name) and owner.id == "lax") \
                    or (isinstance(owner, ast.Attribute)
                        and owner.attr == "lax")
                if not ok:
                    return []
            elif isinstance(func, ast.Name):
                table = self.imports.get(module_path)
                target = table.ext_alias.get(func.id, "") if table else ""
                if not target.startswith("jax.lax"):
                    return []
        out = []
        for idx in _TRACERS[name]:
            if idx < len(call.args):
                out.append(call.args[idx])
        return out

    def _collect_entries(self, module):
        per_name = self.by_name.get(module.path, {})

        def mark_name(fname, static_names):
            for qn in per_name.get(fname, ()):
                key = (module.path, qn)
                self._entries.add(key)
                # a param is static only if EVERY marking says so
                prev = self._entry_static.get(key)
                self._entry_static[key] = (
                    set(static_names) if prev is None
                    else prev & set(static_names))

        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    is_call = isinstance(dec, ast.Call)
                    tn = _terminal_name(dec.func if is_call else dec)
                    static = _static_argnames_of(dec) if is_call else set()
                    if tn in ("jit", "pjit"):
                        mark_name(node.name, static)
                    # @partial(jax.jit, ...) / @functools.partial(jit, ...)
                    if is_call and tn == "partial" and dec.args:
                        inner = _terminal_name(dec.args[0])
                        if inner in ("jit", "pjit"):
                            mark_name(node.name, static)
            elif isinstance(node, ast.Call):
                tn = _terminal_name(node.func)
                static = _static_argnames_of(node) \
                    if tn in ("jit", "pjit") else set()
                for arg in self._fn_args_of_call(node, module.path):
                    if isinstance(arg, ast.Name):
                        mark_name(arg.id, static)
                    # jax.jit(partial(f, ...)) and jax.checkpoint(f)(...)
                    elif isinstance(arg, ast.Call) and arg.args and \
                            _terminal_name(arg.func) == "partial" and \
                            isinstance(arg.args[0], ast.Name):
                        mark_name(arg.args[0].id, static)

    # -- closure -----------------------------------------------------------

    def _callees(self, info: FunctionInfo):
        table = self.imports[info.module_path]
        per_name = self.by_name.get(info.module_path, {})
        out: Set[Tuple[str, str]] = set()
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                for qn in per_name.get(func.id, ()):
                    out.add((info.module_path, qn))
                if func.id in table.func_alias:
                    path, fn = table.func_alias[func.id]
                    for qn in self.by_name.get(path, {}).get(fn, ()):
                        out.add((path, qn))
            elif isinstance(func, ast.Attribute) and \
                    isinstance(func.value, ast.Name):
                owner = func.value.id
                if owner in table.mod_alias:
                    path = table.mod_alias[owner]
                    for qn in self.by_name.get(path, {}).get(func.attr, ()):
                        out.add((path, qn))
            # functions handed onward to tracers from inside traced code
            for arg in self._fn_args_of_call(node, info.module_path):
                if isinstance(arg, ast.Name):
                    for qn in per_name.get(arg.id, ()):
                        out.add((info.module_path, qn))
        # lexically nested defs close over the tracing context: treat
        # them as called (the common `def run(...)` inside `build()`)
        out.update(self._children.get((info.module_path, info.qualname),
                                      ()))
        return out

    def _closure(self):
        seen: Set[Tuple[str, str]] = set()
        frontier = [k for k in self._entries if k in self.functions]
        while frontier:
            key = frontier.pop()
            if key in seen:
                continue
            seen.add(key)
            for nxt in self._callees(self.functions[key]):
                if nxt not in seen and nxt in self.functions:
                    frontier.append(nxt)
        return seen

    # -- queries -----------------------------------------------------------

    def reachable_functions(self, module_path: str):
        """FunctionInfo for every traced-reachable function in a file."""
        return [self.functions[k] for k in self.reachable
                if k[0] == module_path]

    def is_entry(self, module_path: str, qualname: str) -> bool:
        return (module_path, qualname) in self._entries

    def traced_params(self, info: FunctionInfo) -> Set[str]:
        """Parameters PROVABLY traced: an entry function's own params
        minus any the jit site declared static.  Callee/closure params
        may be trace-time Python config, so they return empty — the
        under-approximation that keeps HOST-SYNC's value checks quiet
        on config branching."""
        key = (info.module_path, info.qualname)
        if key not in self._entries:
            return set()
        return info.params - self._entry_static.get(key, set())
