"""``apex_tpu.lint`` — AST-based TPU-hazard analyzer.

The repo's hot paths are guarded by *conventions* the reference enforced
with hand-written CUDA plumbing: tracing discipline (no hyperparameter in
a static jit key — the ~200x retrace pathology PR 1 killed), donation
discipline (never read a buffer after the step that donated it), and
boundary-only collectives (PR 3's one-exchange-per-accumulation-window
invariant).  These are structural properties of the program text, so they
are checkable *before* execution — this package turns each one into a
:class:`~apex_tpu.lint.rules.Rule` over the Python AST, generalizing the
ad-hoc source greps that used to live in ``tests/test_compat.py``.

Surface:

* ``python -m apex_tpu.lint [paths]`` / the ``apex-tpu-lint`` console
  script — exit 0 when the tree is clean, 1 on findings;
* :func:`run` — the programmatic entry (tests, ``bench.py --lint``);
* inline suppressions — ``# tpu-lint: disable=RULE-ID reason`` on the
  flagged line (or the comment line just above it), and
  ``# tpu-lint: disable-file=RULE-ID reason`` anywhere for a whole file;
* a checked-in baseline (:data:`DEFAULT_BASELINE`) grandfathering
  pre-existing findings so new code can't add more.

See ``docs/lint.md`` for the rule catalog with the historical bug behind
each rule.
"""
from .engine import (DEFAULT_BASELINE, Finding, LintResult, load_baseline,
                     run, write_baseline)
from .rules import REGISTRY, Rule, rule_ids

__all__ = [
    "DEFAULT_BASELINE", "Finding", "LintResult", "REGISTRY", "Rule",
    "load_baseline", "run", "rule_ids", "write_baseline",
]
