"""The TPU-hazard rule set.

Every rule encodes an invariant this repo already paid to learn (the PR
that paid is named in each docstring); ``docs/lint.md`` carries the full
catalog with the historical incident behind each one.  Rules are pure
AST passes — conservative by construction: an expression a rule cannot
resolve is dropped, never guessed, so a finding is worth reading.
"""
from __future__ import annotations

import ast
import re
import time
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding, Module

#: scalar hyperparameters that must enter compiled steps TRACED.  Exact
#: identifier / attribute matches only ("grad_accum_steps" is a program
#: *shape* and belongs in static keys; "lr" never does).
HYPERPARAM_NAMES = {
    "lr", "learning_rate", "beta1", "beta2", "betas", "eps",
    "weight_decay", "wd", "momentum", "step", "loss_scale",
}

#: mapped-axis collectives (jax.lax) that must not sit inside an
#: accumulation scan body
COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
               "all_to_all", "ppermute", "psum_scatter"}

#: metadata attributes that are static under tracing — reading them off a
#: traced value is NOT a host sync / traced branch
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}

_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type",
                 "callable", "format", "repr", "str"}


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for the matching Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_strs(node: ast.AST) -> List[str]:
    """String constants in a static_argnames value (str or tuple/list)."""
    out = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
    return out


def _static_key_exprs(call: ast.Call) -> List[ast.AST]:
    """Expressions landing in hashable program-key positions: the
    ``static_key`` of ``step_cache.program``, the ``static_cfg`` /
    ``scaler_cfg`` of the optimizer-step dispatchers, and any keyword
    spelled like one of those anywhere."""
    name = _terminal(call.func)
    out = []
    if name == "program" and len(call.args) >= 2:
        out.append(call.args[1])
    elif name in ("optimizer_step", "optimizer_step_with_scaler"):
        if len(call.args) >= 2:
            out.append(call.args[1])
        if name == "optimizer_step_with_scaler" and len(call.args) >= 5:
            out.append(call.args[4])
    for kw in call.keywords:
        if kw.arg in ("static_key", "static_cfg", "scaler_cfg"):
            out.append(kw.value)
    return out


def _walk_own(root):
    """Walk a function body without descending into nested defs (each
    reachable nested def is visited as its own function)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


class LintContext:
    """Shared analysis context handed to every rule.

    ``dataflow`` is built lazily on first access (rules that never
    consult it keep single-fixture runs AST-only); the engine may pass
    a zero-arg factory so the built interpreter is shared through its
    analysis cache.  ``dataflow_ms`` records build time actually spent
    in THIS run (0 when the cache served it)."""

    def __init__(self, modules: List[Module], callgraph,
                 dataflow=None):
        self.modules = modules
        self.callgraph = callgraph
        self._dataflow = dataflow       # instance, factory, or None
        self.dataflow_ms = 0.0

    @property
    def dataflow(self):
        if self._dataflow is None or callable(self._dataflow):
            from . import dataflow as _df
            t0 = time.perf_counter()
            built = self._dataflow() if callable(self._dataflow) \
                else _df.build(self.modules, self.callgraph)
            self.dataflow_ms = (time.perf_counter() - t0) * 1000.0
            self._dataflow = built
        return self._dataflow


class Rule:
    """Base: subclasses set ``id``/``summary``/``hint`` and implement
    :meth:`check` yielding :class:`Finding`."""
    id: str = ""
    summary: str = ""
    hint: str = ""
    #: True for rules that only judge code inside traced-REACHABLE
    #: functions: whether they examined a given line depends on which
    #: entries the scanned scope contains, so the stale-suppression
    #: audit must not call their directives dead outside that span
    reachability_scoped: bool = False

    def check(self, module: Module, ctx: LintContext):
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(self.id, module.relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message,
                       self.hint if hint is None else hint)


REGISTRY: Dict[str, Rule] = {}


def register(cls):
    REGISTRY[cls.id] = cls()
    return cls


def rule_ids() -> List[str]:
    return sorted(REGISTRY)


def resolve(select=None, ignore=None) -> List[Rule]:
    ids = list(select) if select else rule_ids()
    unknown = [i for i in list(ids) + list(ignore or [])
               if i not in REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule id(s): {unknown}; "
                       f"known: {rule_ids()}")
    ignore = set(ignore or ())
    return [REGISTRY[i] for i in ids if i not in ignore]


# ---------------------------------------------------------------------------
# RETRACE-STATIC
# ---------------------------------------------------------------------------


@register
class RetraceStatic(Rule):
    """Traced hyperparameters in static jit keys — PR 1's ~200x bug.

    A value in ``static_argnames`` (or any hashable program-cache key)
    becomes part of the executable's identity: an lr *schedule* then
    compiles a fresh XLA program every step.  PR 1 measured ~200x step
    overhead from exactly this in the fused optimizers.  Hyperparameters
    must enter as traced device scalars.
    """
    id = "RETRACE-STATIC"
    summary = ("hyperparameter in a static jit key (retraces every "
               "schedule tick)")
    hint = ("pass lr/betas/eps/weight_decay/step as traced device "
            "scalars (jnp.asarray) — see runtime/step_cache.py's hyper "
            "tree; static keys are for program *shape* only")

    def _jit_static_calls(self, call: ast.Call) -> Set[str]:
        """static_argnames a jit/pjit/partial(jit) call declares."""
        from .callgraph import _static_argnames_of
        tn = _terminal(call.func)
        if tn in ("jit", "pjit"):
            return _static_argnames_of(call)
        if tn == "partial" and call.args and \
                _terminal(call.args[0]) in ("jit", "pjit"):
            return _static_argnames_of(call)
        return set()

    def _dataflow_pass(self, module, ctx):
        """The interprocedural half: a TRACED value bound to a declared
        static_argname of a locally-jitted function — invisible to the
        name heuristic when the value is not spelled like a
        hyperparameter (it arrived through helper frames)."""
        jit_static: Dict[str, Set[str]] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                names = self._jit_static_calls(node.value)
                if names:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jit_static.setdefault(tgt.id,
                                                  set()).update(names)
        if not jit_static:
            return
        df = ctx.dataflow
        for info in df.functions_in(module.path):
            for node in _walk_own(info.node):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Name) or \
                        node.func.id not in jit_static:
                    continue
                for kw in node.keywords:
                    if kw.arg not in jit_static[node.func.id]:
                        continue
                    if df.eval_in(info, kw.value).is_traced:
                        yield self.finding(
                            module, kw.value,
                            f"traced value bound to static_argname "
                            f"'{kw.arg}' of '{node.func.id}' — every "
                            f"distinct value retraces (and a live "
                            f"tracer here is a ConcretizationError)")

    def check(self, module, ctx):
        yield from self._dataflow_pass(module, ctx)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            tn = _terminal(node.func)
            # jax.jit(f, static_argnames=...) and partial(jax.jit, ...)
            calls = []
            if tn in ("jit", "pjit"):
                calls.append(node)
            elif tn == "partial" and node.args and \
                    _terminal(node.args[0]) in ("jit", "pjit"):
                calls.append(node)
            for c in calls:
                for kw in c.keywords:
                    if kw.arg != "static_argnames":
                        continue
                    bad = [s for s in _const_strs(kw.value)
                           if s in HYPERPARAM_NAMES]
                    if bad:
                        yield self.finding(
                            module, kw.value,
                            f"hyperparameter(s) {bad} in static_argnames "
                            f"— every schedule change recompiles")
            # hashable step-cache key positions
            for expr in _static_key_exprs(node):
                for sub in ast.walk(expr):
                    name = None
                    if isinstance(sub, ast.Name) and \
                            sub.id in HYPERPARAM_NAMES:
                        name = sub.id
                    elif isinstance(sub, ast.Attribute) and \
                            sub.attr in HYPERPARAM_NAMES:
                        name = _dotted(sub) or sub.attr
                    if name:
                        yield self.finding(
                            module, sub,
                            f"hyperparameter '{name}' embedded in a "
                            f"static program key — one executable per "
                            f"value (schedules recompile every step)")


# ---------------------------------------------------------------------------
# HOST-SYNC
# ---------------------------------------------------------------------------


@register
class HostSync(Rule):
    """Host synchronization inside traced code.

    ``.item()`` / ``jax.device_get`` / ``np.asarray`` / Python ``float()``
    or ``if`` on a traced value blocks dispatch on a device round-trip —
    per call, per step.  Scoped by the intra-package call graph to
    functions reachable from jit entry points, so eager logging loops
    never flag.
    """
    id = "HOST-SYNC"
    reachability_scoped = True
    summary = "host round-trip inside a jit-reachable function"
    hint = ("keep the value on device (jnp ops, lax.cond on traced "
            "flags); fetch for logging OUTSIDE the compiled step — see "
            "the on-device overflow flag in amp/scaler.py for the "
            "pattern")

    def _traced_refs(self, node, is_traced, out):
        """Name nodes referring to traced values, skipping contexts that
        are static under tracing (.shape/.dtype, len(), `is None`).
        ``is_traced(name)`` decides tracedness — the syntactic
        traced-params set widened by the dataflow environment, so a
        value that arrived through helper frames still counts."""
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return
        if isinstance(node, ast.Call) and \
                _terminal(node.func) in _STATIC_CALLS:
            return
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load) and is_traced(node.id):
                out.append(node)
            return
        for child in ast.iter_child_nodes(node):
            self._traced_refs(child, is_traced, out)

    def _walk_own(self, root):
        return _walk_own(root)

    @staticmethod
    def _traced_pred(ctx, info, params):
        """Name -> provably traced, via the syntactic entry-param set or
        the interprocedural dataflow environment."""
        facts = None

        def is_traced(name):
            nonlocal facts
            if name in params:
                return True
            if facts is None:
                facts = ctx.dataflow.facts_for(
                    info.module_path, info.qualname) or ()
            if not facts:
                return False
            v = facts.env.get(name)
            return v is not None and v.is_traced
        return is_traced

    def check(self, module, ctx):
        table = ctx.callgraph.imports.get(module.path)
        np_aliases = {a for a, d in table.ext_alias.items()
                      if d == "numpy"} if table else {"np"}
        for info in ctx.callgraph.reachable_functions(module.path):
            # value-sensitive checks key on provably-traced values: an
            # entry's own non-static params, widened by dataflow facts;
            # .item()/device_get flag in every reachable function UNLESS
            # dataflow proves the operand lives on host
            params = ctx.callgraph.traced_params(info)
            is_traced = self._traced_pred(ctx, info, params)
            for node in self._walk_own(info.node):
                if isinstance(node, ast.Call):
                    yield from self._check_call(module, node, is_traced,
                                                np_aliases, ctx, info)
                elif isinstance(node, (ast.If, ast.While)):
                    refs = []
                    self._traced_refs(node.test, is_traced, refs)
                    if refs:
                        yield self.finding(
                            module, node.test,
                            f"Python `{type(node).__name__.lower()}` on "
                            f"traced value '{refs[0].id}' — the branch "
                            f"forces a device fetch at trace boundaries "
                            f"(use jnp.where / lax.cond)")

    def _check_call(self, module, node, is_traced, np_aliases, ctx, info):
        tn = _terminal(node.func)
        if tn == "item" and isinstance(node.func, ast.Attribute) and \
                not node.args:
            # dataflow re-grounding: an .item() on a value PROVABLY on
            # host (a numpy scalar, a config constant) costs nothing
            if ctx.dataflow.eval_in(info, node.func.value).is_host:
                return
            yield self.finding(
                module, node,
                ".item() inside traced code — blocks on a device "
                "round-trip every step")
            return
        if tn == "device_get":
            if node.args and \
                    ctx.dataflow.eval_in(info, node.args[0]).is_host:
                return
            yield self.finding(
                module, node,
                "jax.device_get inside traced code — host transfer on "
                "the step's critical path")
            return
        if tn in ("asarray", "array") and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in np_aliases and node.args:
            refs = []
            self._traced_refs(node.args[0], is_traced, refs)
            if refs:
                yield self.finding(
                    module, node,
                    f"np.{tn} of traced value '{refs[0].id}' — "
                    f"materializes on host (use jnp.{tn})")
            return
        if tn in ("float", "int", "bool") and \
                isinstance(node.func, ast.Name) and len(node.args) == 1 \
                and not isinstance(node.args[0], ast.Constant):
            refs = []
            self._traced_refs(node.args[0], is_traced, refs)
            if refs:
                yield self.finding(
                    module, node,
                    f"{tn}() of traced value '{refs[0].id}' — host sync "
                    f"(keep it a device scalar)")


# ---------------------------------------------------------------------------
# SCAN-COLLECTIVE
# ---------------------------------------------------------------------------


@register
class ScanCollective(Rule):
    """Collectives inside a ``lax.scan`` body — PR 3's boundary-only
    invariant.

    ``make_train_step(accum_steps=K)`` exists so a K-microbatch window
    costs ONE gradient exchange at the boundary; a ``psum`` inside the
    scan body pays K exchanges.  Syntactic: flags collectives written
    directly in the scanned function (scan bodies that legitimately hop
    per tick — ring attention, pipeline stages — suppress with the
    algorithmic reason).
    """
    id = "SCAN-COLLECTIVE"
    summary = "collective inside a lax.scan body (per-microbatch exchange)"
    hint = ("hoist the collective to the scan boundary (accumulate in "
            "fp32 in the carry, exchange once) — see "
            "training/step.py's accumulation window; if the algorithm "
            "truly hops per step, suppress with the reason")

    def _body_ast(self, module, call: ast.Call):
        body = call.args[0] if call.args else None
        if isinstance(body, ast.Lambda):
            return body
        if isinstance(body, ast.Name):
            # nearest definition ABOVE the scan call (same-name bodies in
            # sibling scopes — e.g. two schedules each with a `tick`)
            best = None
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node.name == body.id and \
                        node.lineno <= call.lineno:
                    if best is None or node.lineno > best.lineno:
                        best = node
            return best
        return None

    def _rotation_only(self, body, sub):
        """A ppermute whose result is bound and never additively
        accumulated is a pure rotation — the loop-carried neighbor hop
        of pipeline/ring schedules.  One hop per tick IS the algorithm
        (nothing to hoist: the exchanged value differs every step), so
        dataflow proves the site clean without a suppression."""
        targets = None
        for st in ast.walk(body):
            if isinstance(st, ast.Assign) and st.value is sub:
                targets = {n.id for t in st.targets
                           for n in ast.walk(t) if isinstance(n, ast.Name)}
                break
        if not targets:
            return False
        for n in ast.walk(body):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Add):
                for side in (n.left, n.right):
                    for m in ast.walk(side):
                        if isinstance(m, ast.Name) and m.id in targets:
                            return False
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.op, ast.Add):
                for m in ast.walk(n):
                    if isinstance(m, ast.Name) and m.id in targets:
                        return False
        return True

    def check(self, module, ctx):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or \
                    _terminal(node.func) != "scan":
                continue
            body = self._body_ast(module, node)
            if body is None:
                continue
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call):
                    continue
                tn = _terminal(sub.func)
                if tn not in COLLECTIVES:
                    continue
                # lax.psum(1, axis) is the axis-size idiom: constant-
                # folded to the mesh size, no collective is emitted
                if tn in ("psum", "pmean", "pmax", "pmin") and sub.args \
                        and isinstance(sub.args[0], ast.Constant):
                    continue
                if tn == "ppermute" and self._rotation_only(body, sub):
                    continue
                yield self.finding(
                    module, sub,
                    f"lax.{tn} inside the lax.scan body at line "
                    f"{node.lineno} — one collective PER scan step, "
                    f"not per window")


# ---------------------------------------------------------------------------
# DONATED-REUSE
# ---------------------------------------------------------------------------


@register
class DonatedReuse(Rule):
    """Reading an argument after donating it.

    ``donate_argnums`` lets XLA write outputs into the input buffers;
    the step-cache donates params/moments/scaler state every step.  Any
    later read of the donated reference sees freed (or overwritten)
    memory.  Tracks, per function, names passed at donated positions of
    a jit-with-donation call site and flags later loads.
    """
    id = "DONATED-REUSE"
    summary = "argument read after being donated to a jit call"
    hint = ("rebind every output of a donating call and drop the input "
            "reference (state = fn(state, ...)); copy first "
            "(jnp.copy) if the pre-step value is really needed")

    def _donated_positions(self, call: ast.Call):
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = tuple(e.value for e in v.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int))
                if out:
                    return out
            # conditional spellings ((0,) if donate else ()) are dynamic
            # — resolved conservatively as "maybe donates nothing"
            return ()
        return None

    def check(self, module, ctx):
        for fn in ast.walk(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(module, fn)

    def _check_function(self, module, fn):
        jitted: Dict[str, Tuple[int, ...]] = {}
        consumed: List[Tuple[str, int, str]] = []  # (name, line, via)
        stores: List[Tuple[str, int]] = []
        loads: List[ast.Name] = []

        def record_call(call, positions):
            for p in positions:
                if p < len(call.args) and \
                        isinstance(call.args[p], ast.Name):
                    consumed.append((call.args[p].id, call.lineno,
                                     _terminal(call.func) or "<fn>"))

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                tn = _terminal(node.value.func)
                if tn in ("jit", "pjit"):
                    pos = self._donated_positions(node.value)
                    if pos:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                jitted[tgt.id] = pos
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) and \
                        node.func.id in jitted:
                    record_call(node, jitted[node.func.id])
                elif isinstance(node.func, ast.Call) and \
                        _terminal(node.func.func) in ("jit", "pjit"):
                    pos = self._donated_positions(node.func)
                    if pos:
                        record_call(node, pos)
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    stores.append((node.id, node.lineno))
                elif isinstance(node.ctx, ast.Load):
                    loads.append(node)

        # ast.walk is breadth-first; reads must be considered in source
        # order or a late rebind would mask an earlier stale read
        loads.sort(key=lambda n: (n.lineno, n.col_offset))
        for name, cline, via in consumed:
            for load in loads:
                if load.id != name or load.lineno <= cline:
                    continue
                # a store on the consuming line itself (`x = fn(x)`) is
                # the sanctioned rebind pattern
                if any(s == name and cline <= sl <= load.lineno
                       for s, sl in stores):
                    break       # rebound; later loads see the new value
                yield self.finding(
                    module, load,
                    f"'{name}' read after being donated to '{via}' at "
                    f"line {cline} — the buffer was invalidated by the "
                    f"call")
                break           # one finding per consumed name


# ---------------------------------------------------------------------------
# COMPAT-SHIM
# ---------------------------------------------------------------------------


@register
class CompatShim(Rule):
    """Direct ``jax.shard_map`` / ``lax.axis_size`` in package code.

    Both are jax>=0.5 spellings: on the 0.4.x runtimes this repo
    supports they are AttributeErrors (the PR 3 satellite that fixed
    ~120 tier-1 failures).  Package code goes through
    ``apex_tpu.compat``; user code may use the modern names because
    ``compat.install()`` polyfills them — so this rule only applies
    inside the apex_tpu package.
    """
    id = "COMPAT-SHIM"
    summary = "direct jax.shard_map / lax.axis_size (breaks on jax 0.4.x)"
    hint = ("use apex_tpu.compat.shard_map / compat.axis_size — the shim "
            "translates check_vma<->check_rep and polyfills 0.4.x")

    def check(self, module, ctx):
        if not module.in_apex_package or \
                module.path.endswith("compat.py"):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute):
                d = _dotted(node)
                if d == "jax.shard_map":
                    yield self.finding(
                        module, node,
                        "direct jax.shard_map — AttributeError on "
                        "jax 0.4.x (compat.shard_map translates the "
                        "check_vma knob)")
                elif d in ("jax.lax.axis_size", "lax.axis_size"):
                    yield self.finding(
                        module, node,
                        "direct lax.axis_size — does not exist on "
                        "jax 0.4.x (compat.axis_size uses the psum(1) "
                        "idiom there)")
                elif d and d.startswith("jax.experimental.shard_map"):
                    yield self.finding(
                        module, node,
                        "jax.experimental.shard_map referenced directly "
                        "— removed on modern jax; the shim owns version "
                        "dispatch")
            elif isinstance(node, ast.ImportFrom) and \
                    (node.module or "").startswith(
                        "jax.experimental.shard_map"):
                yield self.finding(
                    module, node,
                    "import from jax.experimental.shard_map — removed "
                    "on modern jax; route through apex_tpu.compat")


# ---------------------------------------------------------------------------
# UNBOUNDED-COLLECTIVE
# ---------------------------------------------------------------------------


@register
class UnboundedCollective(Rule):
    """Process-wide collectives outside the bounded wrapper — PR 2.

    ``multihost_utils`` calls block until EVERY process arrives; one
    preempted host hangs the job forever with no diagnosis.  PR 2's
    ``timed_flat_dist_call`` (parallel/distributed.py) wraps them with a
    deadline and names the missing ranks on timeout — everything
    process-wide goes through it.
    """
    id = "UNBOUNDED-COLLECTIVE"
    summary = "raw multihost collective (no deadline, no missing-rank "\
              "diagnosis)"
    hint = ("route through apex_tpu.parallel.timed_flat_dist_call "
            "(deadline + CollectiveTimeoutError naming absent ranks) — "
            "see runtime/resilience.py's bounded init")

    def check(self, module, ctx):
        if module.path.replace("\\", "/").endswith(
                "apex_tpu/parallel/distributed.py"):
            return      # the sanctioned wrapper home
        locals_from_mhu: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                m = node.module or ""
                if "multihost_utils" in m:
                    yield self.finding(
                        module, node,
                        f"import from {m} — unbounded process-wide "
                        f"collective surface")
                    locals_from_mhu |= {al.asname or al.name
                                        for al in node.names}
                elif m == "jax.experimental":
                    for al in node.names:
                        if al.name == "multihost_utils":
                            yield self.finding(
                                module, node,
                                "import of jax.experimental."
                                "multihost_utils — unbounded collective "
                                "surface")
            elif isinstance(node, ast.Import):
                for al in node.names:
                    if "multihost_utils" in al.name:
                        yield self.finding(
                            module, node,
                            f"import {al.name} — unbounded collective "
                            f"surface")
            elif isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                tn = _terminal(node.func)
                if "multihost_utils" in d:
                    yield self.finding(
                        module, node,
                        f"{d} call — blocks until every process "
                        f"arrives, with no deadline")
                elif tn in locals_from_mhu and \
                        isinstance(node.func, ast.Name):
                    yield self.finding(
                        module, node,
                        f"{tn}() (from multihost_utils) — blocks until "
                        f"every process arrives, with no deadline")


# ---------------------------------------------------------------------------
# IMPURE-STATIC-KEY
# ---------------------------------------------------------------------------

_IMPURE_OWNERS = {"random", "secrets"}
_IMPURE_CALLS = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("time", "perf_counter_ns"), ("os", "urandom"),
    ("uuid", "uuid1"), ("uuid", "uuid4"),
    ("datetime", "now"), ("datetime", "utcnow"),
}


@register
class ImpureStaticKey(Rule):
    """Wall-clock / RNG values feeding program-cache keys.

    A static key exists to make "same program" hashable; ``time.time()``
    or ``random.*`` in that position makes every call a distinct program
    — silent unbounded recompilation (and cache-stats that lie).  Also
    flags ``id(...)``: stable within a process but not across restarts,
    so resumed runs silently recompile everything.
    """
    id = "IMPURE-STATIC-KEY"
    summary = "impure value (time/random/id) in a static program key"
    hint = ("key on stable program *shape* (config tuples, treedefs, "
            "shapes/dtypes, monotonic builder tokens) — see "
            "training/step.py's _STEP_TOKENS for the per-builder "
            "pattern")

    def _impure_calls(self, expr, module) -> Iterable[ast.Call]:
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            tn = _terminal(sub.func)
            if isinstance(sub.func, ast.Name) and tn == "id":
                yield sub
                continue
            if isinstance(sub.func, ast.Attribute):
                d = _dotted(sub.func) or ""
                parts = d.split(".")
                if len(parts) >= 2:
                    owner, leaf = parts[-2], parts[-1]
                    if (owner, leaf) in _IMPURE_CALLS or \
                            owner in _IMPURE_OWNERS or \
                            (owner == "random" or
                             ".random." in f".{d}"):
                        yield sub

    def check(self, module, ctx):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            for expr in _static_key_exprs(node):
                for bad in self._impure_calls(expr, module):
                    d = _dotted(bad.func) or _terminal(bad.func)
                    yield self.finding(
                        module, bad,
                        f"{d}(...) inside a static program key — every "
                        f"call keys a new executable (unbounded "
                        f"recompilation)")


# ---------------------------------------------------------------------------
# CKPT-ATOMIC
# ---------------------------------------------------------------------------

_CKPT_PATH_RE_SRC = r"(ckpt|checkpoint|\.pkl)"


@register
class CkptAtomic(Rule):
    """Checkpoint bytes written outside the atomic path — PR 8 (elastic).

    ``runtime/resilience.py``'s ``write_checkpoint_file`` is THE
    checkpoint write path: tmp file + fsync + one ``os.rename`` (+
    directory fsync), a manifest with per-component CRC32, and — since
    schema 2 — the sharding layout the elastic restore reshards by.  A
    raw ``pickle.dump`` / ``open(..., "wb")`` checkpoint write has none
    of that: a preemption mid-write corrupts the only copy at its final
    path, and the file can be neither validated nor resharded after a
    topology change.  The elastic recovery cycle (re-plan + reshard)
    only works when every checkpoint carries the schema-2 metadata, so
    every write must go through the one path."""

    id = "CKPT-ATOMIC"
    summary = "checkpoint written outside the atomic tmp+fsync+rename path"
    hint = ("route through runtime/resilience.py: write_checkpoint_file / "
            "CheckpointManager.save (atomic rename, CRC32 manifest, "
            "schema-2 sharding layout for elastic restore)")

    def check(self, module: Module, ctx) -> Iterable[Finding]:
        if module.path.replace("\\", "/").endswith(
                "apex_tpu/runtime/resilience.py"):
            return      # the sanctioned write path itself
        import re as _re
        ckpt_re = _re.compile(_CKPT_PATH_RE_SRC, _re.IGNORECASE)
        dump_aliases: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module in ("pickle", "cPickle", "dill"):
                dump_aliases |= {al.asname or al.name
                                 for al in node.names if al.name == "dump"}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            tn = _terminal(node.func)
            if d.endswith("pickle.dump") or d == "dill.dump" or \
                    (isinstance(node.func, ast.Name)
                     and tn in dump_aliases):
                yield self.finding(
                    module, node,
                    f"{d or tn}(...) writes a pickle stream straight to "
                    f"a file — no atomic rename, no manifest, no "
                    f"checksum, no sharding layout")
            elif isinstance(node.func, ast.Name) and tn == "open" \
                    and self._binary_write_mode(node) \
                    and self._names_checkpoint(node, ckpt_re):
                yield self.finding(
                    module, node,
                    "binary-mode open() of a checkpoint path — a "
                    "preemption mid-write leaves a partial file at the "
                    "final path")

    @staticmethod
    def _binary_write_mode(call: ast.Call) -> bool:
        mode = call.args[1] if len(call.args) >= 2 else None
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            m = mode.value
            return "b" in m and any(c in m for c in "wax+")
        return False

    @staticmethod
    def _names_checkpoint(call: ast.Call, ckpt_re) -> bool:
        # conservative: only const path expressions (f-strings included)
        # can be matched; a variable path is dropped, never guessed
        if not call.args:
            return False
        for sub in ast.walk(call.args[0]):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str) and \
                    ckpt_re.search(sub.value):
                return True
        return False


# ---------------------------------------------------------------------------
# OBS-IN-JIT
# ---------------------------------------------------------------------------

#: observe names that are jit-safe BY DESIGN: the pure on-device telemetry
#: constructors the fused step folds into its donated carry.
_OBS_JIT_SAFE = {"accumulate", "init_telemetry", "StepTelemetry"}

#: the host-side observe submodules (telemetry — the on-device surface —
#: is deliberately absent)
_OBS_SUBMODULES = {"registry", "spans", "watchdog"}


@register
class ObsInJit(Rule):
    """Host-side observe calls inside traced code — the observe PR.

    Every ``apex_tpu.observe`` surface except the telemetry carry is
    host machinery: registry counters take locks and append to deques,
    spans read wall clocks and write JSONL sinks, the watchdog heartbeat
    touches thread state.  Traced, such a call runs ONCE at trace time
    and never again — silently dead telemetry (the counter sticks at its
    trace-time value, the span measures tracing, not execution) — and
    draining the telemetry carry inside jit would force the host sync
    the carry exists to avoid.  On-device accumulation belongs in
    ``observe.telemetry`` (jit-safe by construction); spans, counters,
    events, heartbeats and drains belong in the eager driver.
    """
    id = "OBS-IN-JIT"
    reachability_scoped = True
    summary = "host-side observe call inside a jit-reachable function"
    hint = ("accumulate on device via observe.telemetry (the fused "
            "step's telem carry) and log OUTSIDE the compiled step — "
            "spans/counters/events/drains belong in the eager driver; "
            "see TrainStep.drain_telemetry for the boundary")

    def _observe_bindings(self, module, ctx):
        """Local names bound to the host-side observe surface:
        ``mods`` (alias -> observe submodule) and ``funcs`` (alias ->
        imported observe callable).  Resolved through the analyzed set
        when the package is in it, through external dotted names when
        the engine is pointed at a file outside it."""
        mods: Dict[str, str] = {}
        funcs: Dict[str, str] = {}
        table = ctx.callgraph.imports.get(module.path)
        if table is None:
            return mods, funcs

        def _host_observe_path(p):
            p = p.replace("\\", "/")
            if p.endswith("/observe/telemetry.py"):
                return None         # the jit-safe on-device surface
            return p if "/observe/" in p else None

        for local, path in table.mod_alias.items():
            if _host_observe_path(path):
                mods[local] = path
        for local, (path, fn) in table.func_alias.items():
            if path.replace("\\", "/").endswith("/observe/__init__.py") \
                    and fn not in _OBS_JIT_SAFE and fn != "telemetry":
                funcs[local] = fn
        for local, dotted in table.ext_alias.items():
            if dotted.endswith(".observe") or dotted == "observe":
                mods[local] = dotted
            elif ".observe." in f".{dotted}":
                tail = dotted.rsplit(".", 1)[1]
                if tail in _OBS_SUBMODULES:
                    mods[local] = dotted
                elif tail not in _OBS_JIT_SAFE and tail != "telemetry":
                    funcs[local] = tail
        return mods, funcs

    def _walk_own(self, root):
        """Function body sans nested defs (each reachable nested def is
        visited as its own function)."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def check(self, module, ctx):
        mods, funcs = self._observe_bindings(module, ctx)
        for info in ctx.callgraph.reachable_functions(module.path):
            for node in self._walk_own(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = self.flag_for(node, mods, funcs)
                if f is not None:
                    yield self.finding(module, node, f)

    def flag_for(self, node: ast.Call, mods, funcs) -> Optional[str]:
        tn = _terminal(node.func)
        if tn == "drain_telemetry":
            # any spelling, including self.drain_telemetry(): the drain
            # fetches the carry to host BY DESIGN — only legal outside
            return ("drain_telemetry() inside traced code — the drain "
                    "is the host fetch the telemetry carry defers; it "
                    "belongs outside the compiled step")
        if isinstance(node.func, ast.Name) and node.func.id in funcs:
            return (f"observe.{funcs[node.func.id]}(...) inside traced "
                    f"code — runs once at trace time, never per step "
                    f"(dead telemetry)")
        if isinstance(node.func, ast.Attribute):
            owner = node.func.value
            if isinstance(owner, ast.Name) and owner.id in mods and \
                    tn not in _OBS_JIT_SAFE:
                return (f"{owner.id}.{tn}(...) resolves into "
                        f"apex_tpu.observe's host surface inside traced "
                        f"code — runs once at trace time, never per "
                        f"step (dead telemetry)")
            d = _dotted(node.func) or ""
            if ".observe." in f".{d}" and ".telemetry." not in d and \
                    tn not in _OBS_JIT_SAFE:
                return (f"{d}(...) inside traced code — the observe "
                        f"host surface runs once at trace time, never "
                        f"per step (dead telemetry)")
        return None


# ---------------------------------------------------------------------------
# EXEC-BYPASS
# ---------------------------------------------------------------------------

#: function names that are, by this repo's convention, whole train/opt
#: step programs.  Exact matches plus the ``*_step_fn`` suffix — the
#: conservative set: inference ``run`` closures and generic helpers never
#: match.
_STEP_FN_NAMES = {"step_fn", "jit_step", "train_step", "zero_train_step"}

#: executor modules that legitimately compile/count dispatches: the
#: executor itself and the cache whose counters it bumps
_EXEC_HOMES = ("apex_tpu/runtime/executor.py",
               "apex_tpu/runtime/step_cache.py")


@register
class ExecBypass(Rule):
    """Step programs compiled or dispatched outside the one-runtime
    executor — the one-runtime PR.

    Before the executor, the eager optimizer surface and the fused train
    step each had their own route into the step-program cache; donation
    policy, dispatch counters and span/heartbeat plumbing drifted apart
    (the eager path had no heartbeats at all, so the stall watchdog was
    blind to half the library).  ``runtime/executor.py`` is now the one
    place ``jax.jit`` is called on a step program and the one place
    dispatches are counted.  Flags, outside the executor: direct
    ``step_cache.program(...)`` compile-or-hit calls, manual
    ``_bump("dispatches", ...)`` counter writes, and ``jax.jit`` of a
    function named like a train step.  Wrappers describe a
    ``Program`` and ``executor.submit`` it instead.
    """
    id = "EXEC-BYPASS"
    summary = ("step program compiled/dispatched outside "
               "runtime/executor.py")
    hint = ("describe the step as a runtime.executor.Program (static_key, "
            "donate_argnums, optional wrap/shardings) and dispatch via "
            "executor.submit — compiles, counters, dispatch spans and "
            "watchdog heartbeats then come uniformly; see "
            "docs/executor.md's migration table")

    @staticmethod
    def _is_step_name(name: Optional[str]) -> bool:
        return bool(name) and (name in _STEP_FN_NAMES
                               or name.endswith("_step_fn"))

    def check(self, module, ctx):
        path = module.path.replace("\\", "/")
        if path.endswith(_EXEC_HOMES):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            tn = _terminal(node.func)
            d = _dotted(node.func) or ""
            if tn == "program" and "step_cache" in d.split("."):
                yield self.finding(
                    module, node,
                    f"{d}(...) — direct step-cache compile-or-hit "
                    f"outside the executor (no dispatch count, no "
                    f"span, no heartbeat)")
            elif tn == "_bump" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value == "dispatches":
                yield self.finding(
                    module, node,
                    "manual _bump('dispatches', ...) — dispatch "
                    "counting belongs to executor.submit")
            elif tn in ("jit", "pjit") and node.args:
                target = node.args[0]
                name = None
                if isinstance(target, ast.Name):
                    name = target.id
                elif isinstance(target, ast.Attribute):
                    name = target.attr
                if self._is_step_name(name):
                    yield self.finding(
                        module, node,
                        f"jax.jit of step function '{name}' outside the "
                        f"executor — the program bypasses the cache "
                        f"stats, donation policy and observability")


# ---------------------------------------------------------------------------
# SERVE-SHAPE
# ---------------------------------------------------------------------------

#: the serving program kinds (runtime/executor.py SERVE_KINDS) — string
#: literals only; a kind the rule cannot resolve is not guessed
_SERVE_PROGRAM_KINDS = {"prefill_step", "decode_step",
                        "draft_prefill_step", "spec_verify_step"}

#: attribute reads that surface a request-dependent extent
_SHAPE_ATTRS = {"shape", "size", "ndim"}

#: identifiers carrying a speculative tick's ragged acceptance count —
#: 1..k+1 per sequence per tick, the most request-dependent extent in
#: the serve path.  Matched by name because the value is a plain host
#: int by the time it could steer a program (``n_acc``, ``accepted_len``
#: and the like); routing through ``bucket*`` launders it exactly like
#: any other extent.
_ACCEPT_NAME_RE = re.compile(r"accept|(^|_)n_acc(_|$)")


def _serve_kind_of(call: ast.Call) -> Optional[str]:
    """The serving kind a ``Program(...)`` construction names, when the
    kind (first positional or ``kind=``) is a serve-kind string literal;
    else None."""
    if _terminal(call.func) != "Program":
        return None
    kind = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "kind":
            kind = kw.value
    if isinstance(kind, ast.Constant) and kind.value in _SERVE_PROGRAM_KINDS:
        return kind.value
    return None


def _serve_static_key(call: ast.Call) -> Optional[ast.AST]:
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "static_key":
            return kw.value
    return None


@register
class ServeShape(Rule):
    """Request-dependent shapes reaching serving programs — PR 12.

    A serving engine sees arbitrary prompt lengths, batch occupancies
    and block-table lengths; the step cache keys programs by (kind,
    static_key, operand signature).  Let a raw per-request extent —
    ``len(prompt)``, ``tokens.shape``, ``len(table)`` — reach a
    serve-kind static key (``prefill_step`` / ``decode_step`` /
    ``draft_prefill_step`` / ``spec_verify_step``) or steer which
    program gets built, and every distinct request length compiles a
    fresh executable: recompilation scales with TRAFFIC, not with
    config, and tail latency spikes exactly when load does.  The serve
    engine's discipline is a bucket table: every dynamic extent is
    rounded up through ``serve.scheduler.bucket`` (powers of two capped
    at the config maximum) before it touches program identity, so the
    shape set is ``O(log·log)`` and decode is recompile-free after
    warmup.  Speculative decoding adds the worst extent of all: the
    per-tick ragged acceptance count (``n_acc``/``accepted_len``, 1..k+1
    PER SEQUENCE PER TICK) — key or steer a program on it raw and the
    engine recompiles mid-stream on the first tick whose acceptance
    pattern is new (the PR 16 incident; docs/lint.md).  Flags, on
    serve-kind ``Program(...)`` constructions: ``len(...)`` /
    ``.shape`` / ``.size`` / ``.ndim`` / acceptance-count identifiers
    inside the static key unless routed through a ``bucket*`` call,
    and ``if``/``while`` tests on those extents inside the functions
    that build the programs (per-request program selection is the same
    recompile surface by another route).
    """
    id = "SERVE-SHAPE"
    summary = ("request-dependent shape in a serving program key / "
               "build path (recompiles per request, not per bucket)")
    hint = ("round every request-dependent extent — lengths, shapes, "
            "and speculative acceptance counts alike — through the "
            "bucket table (serve.scheduler.bucket: next power of two, "
            "capped at the config maximum) before it reaches a Program "
            "static key or build-time branch — operand signatures then "
            "complete the cache key and decode re-hits after warmup; "
            "ragged acceptance belongs in operand VALUES (the host "
            "commit loop), never in program identity; see "
            "docs/serving.md's keying discipline")

    def _dynamic_exprs(self, expr):
        """``len()`` calls, ``.shape``/``.size``/``.ndim`` reads, and
        acceptance-count identifiers (``n_acc``/``accepted_len``/...)
        in ``expr`` that are NOT routed through a ``bucket*`` call —
        descent stops at any call whose name contains ``bucket``: its
        result is by construction one of O(log) values."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                tn = _terminal(node.func) or ""
                if "bucket" in tn:
                    continue
                if isinstance(node.func, ast.Name) and tn == "len":
                    yield node, "len(...)"
                    continue
            if isinstance(node, ast.Attribute) and \
                    node.attr in _SHAPE_ATTRS:
                yield node, f".{node.attr}"
                continue
            if isinstance(node, ast.Name) and \
                    _ACCEPT_NAME_RE.search(node.id):
                yield node, f"raw acceptance count '{node.id}'"
                continue
            stack.extend(ast.iter_child_nodes(node))

    def check(self, module, ctx):
        serve_calls = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _serve_kind_of(node):
                serve_calls.append(node)
        if not serve_calls:
            return
        for call in serve_calls:
            kind = _serve_kind_of(call)
            key = _serve_static_key(call)
            if key is None:
                continue
            for bad, what in self._dynamic_exprs(key):
                yield self.finding(
                    module, bad,
                    f"{what} in the '{kind}' program's static key — "
                    f"the key tracks a per-request extent, so every "
                    f"new request length compiles a fresh executable")
        # build-time branches on raw extents, in the functions that
        # lexically construct the serving programs
        ids = {id(c) for c in serve_calls}
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(id(n) in ids for n in ast.walk(fn)):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                for bad, what in self._dynamic_exprs(node.test):
                    yield self.finding(
                        module, bad,
                        f"{what} steering a "
                        f"{'while' if isinstance(node, ast.While) else 'if'}"
                        f" in serving-program build code — per-request "
                        f"program selection recompiles per request "
                        f"length, not per bucket")


# ---------------------------------------------------------------------------
# KERNEL-FALLBACK
# ---------------------------------------------------------------------------


def _in_kernels_package(module: Module) -> bool:
    """True for files living in ``apex_tpu/kernels/`` — the one place a
    raw ``pallas_call`` may appear."""
    dotted = module.dotted or ""
    if dotted == "apex_tpu.kernels" or \
            dotted.startswith("apex_tpu.kernels."):
        return True
    rel = "/" + module.relpath.replace("\\", "/")
    return "/apex_tpu/kernels/" in rel


@register
class KernelFallback(Rule):
    """Hand-written kernels without a declared escape hatch — PR 13.

    Rounds 4-5 measured most of this repo's hand-written Pallas kernels
    LOSING to XLA's own lowering on real shapes (norms 0.93-1.03x,
    fused LM-head chain 0.69x at GPT-2 shapes; flash attention only
    wins >= 512 keys).  A ``pallas_call`` wired straight into a model
    path locks those losses in: there is no seam to route the losing
    shapes back to XLA, and no probe record to ever find out.  The
    discipline is the ``apex_tpu.kernels`` tier: every kernel lives in
    that package and registers through ``register_kernel`` with a
    declared ``xla_fallback`` (the dotted path dispatch falls back to)
    and a ``threshold_probe`` (the measured win region encoded as
    data), so the calibration ledger — not the author's optimism —
    decides dispatch per (chip, shape).  Flags: any ``pallas_call``
    call or import outside ``apex_tpu/kernels/``, and a
    ``register_kernel(...)`` missing a usable ``xla_fallback`` or
    ``threshold_probe``.
    """
    id = "KERNEL-FALLBACK"
    summary = ("pallas_call outside the kernels tier, or a kernel "
               "registered without a declared XLA fallback + threshold "
               "probe")
    hint = ("move the kernel into apex_tpu/kernels/ and register it: "
            "register_kernel(name, xla_fallback='<dotted path of the "
            "XLA implementation>', threshold_probe=<fn(dims) -> "
            "(threshold, use_pallas)>) — dispatch.decide() then "
            "consults the calibration ledger and falls back below the "
            "measured win region; see docs/kernels.md")

    def _missing(self, call: ast.Call) -> List[str]:
        """Registration keywords absent or constant-empty."""
        kws = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        out = []
        for need in ("xla_fallback", "threshold_probe"):
            val = kws.get(need)
            if val is None:
                out.append(need)
            elif isinstance(val, ast.Constant) and not val.value:
                out.append(f"{need} (empty)")
        return out

    def check(self, module, ctx):
        in_kernels = _in_kernels_package(module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and not in_kernels:
                for alias in node.names:
                    if alias.name == "pallas_call":
                        yield self.finding(
                            module, node,
                            "pallas_call imported outside "
                            "apex_tpu/kernels/ — hand-written kernels "
                            "belong in the measured-dispatch tier, not "
                            "wired raw into model code")
            if not isinstance(node, ast.Call):
                continue
            name = _terminal(node.func)
            if name == "pallas_call" and not in_kernels:
                yield self.finding(
                    module, node,
                    "raw pallas_call outside apex_tpu/kernels/ — no "
                    "XLA fallback seam, no probe record: losing shapes "
                    "(round-5: norms 0.93-1.03x, lm_head chain 0.69x) "
                    "can never route back to XLA")
            elif name == "register_kernel":
                missing = self._missing(node)
                if missing:
                    yield self.finding(
                        module, node,
                        "kernel registered without " + " / ".join(missing)
                        + " — dispatch cannot fall back to XLA below "
                        "the win region, and the ledger has no default "
                        "threshold to override")


# ---------------------------------------------------------------------------
# PRECISION-SINK / TRACER-LEAK / SHAPE-BRANCH — the dataflow-native rules
# ---------------------------------------------------------------------------

#: reductions/contractions where a half-precision input silently becomes
#: a half-precision ACCUMULATOR unless told otherwise
_REDUCTION_CALLS = {"sum", "mean", "prod", "cumsum", "cumprod", "dot",
                    "matmul", "tensordot", "vdot", "einsum"}

#: container mutators that smuggle a value past the end of the trace
_LEAK_MUTATORS = {"append", "add", "extend", "insert", "setdefault",
                  "update"}


@register
class PrecisionSink(Rule):
    """Half-precision values reaching a reduction without an fp32
    accumulator — the amp-O2 master-weight invariant as a rule.

    PR 4's loss-scaling work exists because fp16 overflows at 65504 and
    bf16 drops mantissa bits; both are fine for *storage* and matmul
    inputs but fatal for *accumulation*.  ``jnp.sum`` of an fp16 array
    accumulates IN fp16 unless ``preferred_element_type``/``dtype`` says
    otherwise.  The dtype lattice proves where a half value flows into a
    reduction with no fp32 upcast on any path — a proof, not a guess:
    an operand the dataflow cannot type never flags.
    """
    id = "PRECISION-SINK"
    reachability_scoped = True
    summary = "fp16/bf16 value reduced/accumulated without fp32 upcast"
    hint = ("accumulate in fp32: pass preferred_element_type="
            "jnp.float32 (dot/matmul/einsum), dtype=jnp.float32 "
            "(sum/mean/prod), or upcast with .astype(jnp.float32) "
            "first — see the master-weight chain in amp/amp.py")

    def _module_aliases(self, module, ctx):
        table = ctx.callgraph.imports.get(module.path)
        names = {"jnp", "np", "jax", "lax", "math"}
        if table:
            names |= set(table.ext_alias) | set(table.mod_alias)
        return names

    def _folded_dtype(self, df, info, call, mod_names):
        """Promoted dtype of the array operands (args + non-module
        receiver), skipping einsum subscript strings."""
        from . import dataflow as _df
        operands = [a for a in call.args
                    if not (isinstance(a, ast.Constant)
                            and isinstance(a.value, str))]
        if isinstance(call.func, ast.Attribute):
            recv = call.func.value
            if not (isinstance(recv, ast.Name) and recv.id in mod_names):
                operands.append(recv)
        if not operands or any(isinstance(a, ast.Starred)
                               for a in operands):
            return _df.DT_UNKNOWN
        dt = _df.DT_WEAK
        for a in operands:
            dt = _df.promote_dtype(dt, df.eval_in(info, a).dtype)
        return dt

    def _exempt(self, call):
        from . import dataflow as _df
        for kw in call.keywords:
            if kw.arg == "preferred_element_type":
                return True
            if kw.arg in ("dtype", "accumulator_dtype") and \
                    _df.dtype_const(kw.value) not in _df.HALF_DTYPES:
                return True
        return False

    def check(self, module, ctx):
        from . import dataflow as _df
        mod_names = None
        for info in ctx.callgraph.reachable_functions(module.path):
            df = ctx.dataflow
            if mod_names is None:
                mod_names = self._module_aliases(module, ctx)
            for node in _walk_own(info.node):
                if isinstance(node, ast.Call):
                    tn = _terminal(node.func)
                    if tn not in _REDUCTION_CALLS or self._exempt(node):
                        continue
                    dt = self._folded_dtype(df, info, node, mod_names)
                    if dt in _df.HALF_DTYPES:
                        yield self.finding(
                            module, node,
                            f"half-precision operand reaches {tn}() — "
                            f"the accumulator inherits the half dtype "
                            f"(fp16 saturates at 65504)")
                elif isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.MatMult):
                    if df.eval_in(info, node).is_half:
                        yield self.finding(
                            module, node,
                            "half @ half matmul accumulates in half "
                            "precision — pass preferred_element_type="
                            "jnp.float32 via jnp.matmul, or upcast")
                elif isinstance(node, (ast.For, ast.While)):
                    yield from self._loop_accum(module, df, info, node)

    def _loop_accum(self, module, df, info, loop):
        """`acc += h` / `acc = acc + h` in a python loop: each iteration
        adds in half precision."""
        for st in ast.walk(loop):
            if isinstance(st, ast.AugAssign) and \
                    isinstance(st.op, ast.Add):
                if df.eval_in(info, st.value).is_half:
                    yield self.finding(
                        module, st,
                        "loop accumulation of a half-precision value — "
                        "running sum saturates/rounds in fp16/bf16")
            elif isinstance(st, ast.Assign) and \
                    isinstance(st.value, ast.BinOp) and \
                    isinstance(st.value.op, ast.Add) and \
                    len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name):
                tgt = st.targets[0].id
                sides = (st.value.left, st.value.right)
                if any(isinstance(s, ast.Name) and s.id == tgt
                       for s in sides) and \
                        df.eval_in(info, st.value).is_half:
                    yield self.finding(
                        module, st,
                        "loop accumulation of a half-precision value — "
                        "running sum saturates/rounds in fp16/bf16")


def _leftmost_name(node):
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


@register
class TracerLeak(Rule):
    """Traced value stored into state that outlives the trace.

    A tracer written to a module global, an instance attribute, or a
    long-lived container during tracing becomes a corpse the moment the
    trace ends: touching it later raises
    ``UnexpectedTracerError`` (best case) or silently bakes one
    example's abstract value into every future step (worst case — jax
    calls this the leaked-tracer bug).  Dataflow knows which values are
    tracers and which names are module-level, so the rule fires only on
    proven leaks.
    """
    id = "TRACER-LEAK"
    reachability_scoped = True
    summary = "traced value escapes into state that outlives the trace"
    hint = ("return the value from the traced function instead (carry "
            "it through the step's outputs); host-side stores belong "
            "outside the jit boundary — see how observe/ keeps "
            "telemetry in the carry")

    def _store_desc(self, target, gdecls, mg, local):
        if isinstance(target, ast.Name):
            if target.id in gdecls:
                return f"module global '{target.id}'"
            return None
        base = _leftmost_name(target)
        if base in ("self", "cls"):
            return f"instance state '{base}.…'"
        if base and base in mg and base not in local:
            kind = ("module-level container"
                    if isinstance(target, ast.Subscript)
                    else "module-global attribute")
            return f"{kind} '{base}'"
        return None

    def check(self, module, ctx):
        for info in ctx.callgraph.reachable_functions(module.path):
            df = ctx.dataflow
            mg = df.module_globals(module.path)
            facts = df.facts_for(info.module_path, info.qualname)
            local = set(facts.env) if facts is not None else set()
            gdecls = set()
            for n in _walk_own(info.node):
                if isinstance(n, ast.Global):
                    gdecls.update(n.names)
            for n in _walk_own(info.node):
                if isinstance(n, (ast.Assign, ast.AugAssign,
                                  ast.AnnAssign)):
                    value = n.value
                    if value is None or \
                            not df.eval_in(info, value).is_traced:
                        continue
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in targets:
                        desc = self._store_desc(t, gdecls, mg, local)
                        if desc:
                            yield self.finding(
                                module, n,
                                f"traced value stored into {desc} — "
                                f"outlives the trace (leaked tracer)")
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _LEAK_MUTATORS and n.args:
                    desc = self._store_desc(n.func, gdecls, mg, local)
                    if desc and any(df.eval_in(info, a).is_traced
                                    for a in n.args):
                        yield self.finding(
                            module, n,
                            f"traced value .{n.func.attr}()-ed into "
                            f"{desc} — outlives the trace (leaked "
                            f"tracer)")


@register
class ShapeBranch(Rule):
    """Python control flow on a traced value's shape — SERVE-SHAPE's
    program-identity hazard, generalized beyond serve.

    Shapes ARE concrete at trace time, so ``if x.shape[0] > n:`` runs —
    but each distinct shape now takes its own branch and keys its own
    executable, which is exactly how the serve path melted before
    bucketing (PR 9): continuous batching feeds every length that
    arrives.  The dataflow ``shape_derived`` flag follows shape reads
    through arithmetic and helpers; routing through any ``bucket*``
    quantizer clears it (the sanctioned O(log) program count).
    Raise/assert-only guards are validation, not program forks, and
    stay exempt.
    """
    id = "SHAPE-BRANCH"
    reachability_scoped = True
    summary = "python branch/loop on a traced value's shape"
    hint = ("quantize first (bucket_len / next_bucket-style helper) so "
            "the program count stays O(log max) — or move the decision "
            "on-device with jnp.where / lax.cond; see "
            "docs/serving.md on shape buckets")

    #: name fragments of sanctioned shape-quantizer helpers: branches
    #: INSIDE them are how the O(log) program count gets computed
    _QUANTIZER_NAMES = ("bucket", "block", "round_up", "chunk")

    def _is_pad_guard(self, node):
        """``if padded != raw: x = jnp.pad(...)`` — pad-to-multiple.
        Both paths converge on the quantized extent, so the branch does
        not fork program identity."""
        if not isinstance(node, ast.If) or node.orelse:
            return False
        for s in node.body:
            if not (isinstance(s, ast.Assign)
                    and isinstance(s.value, ast.Call)
                    and _terminal(s.value.func) == "pad"):
                return False
        return bool(node.body)

    def check(self, module, ctx):
        for info in ctx.callgraph.reachable_functions(module.path):
            name = info.name.lower()
            if any(q in name for q in self._QUANTIZER_NAMES):
                continue
            for node in _walk_own(info.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if isinstance(node, ast.If) and not node.orelse and \
                        all(isinstance(s, (ast.Raise, ast.Assert))
                            for s in node.body):
                    continue   # shape-validation guard, not a fork
                if self._is_pad_guard(node):
                    continue
                val = ctx.dataflow.eval_in(info, node.test)
                if val.shape_derived:
                    kw = type(node).__name__.lower()
                    yield self.finding(
                        module, node.test,
                        f"`{kw}` on a shape-derived value — every "
                        f"distinct input shape takes its own branch "
                        f"and compiles its own program")


# ---------------------------------------------------------------------------
# STALE-SUPPRESSION — engine-driven: the directive audit
# ---------------------------------------------------------------------------


@register
class StaleSuppression(Rule):
    """``# tpu-lint: disable=RULE`` comments whose rule no longer fires
    on that line.

    Suppressions are debt with a reason attached; when the analyzer
    gets precise enough to prove the site clean (as dataflow did for
    the pipeline ppermute hops), the directive outlives its finding and
    silently masks FUTURE regressions on the same line.  The engine
    tracks which directives matched a finding during the run and
    reports the unmatched remainder here — a rule id, so it selects,
    suppresses and baselines like any other.
    """
    id = "STALE-SUPPRESSION"
    summary = "suppression directive whose rule no longer fires here"
    hint = ("delete the directive — the analyzer proves the line "
            "clean; if the hazard is real but currently unprovable, "
            "keep it and note why in the reason")

    #: the engine emits these findings after the rule loop (it owns the
    #: directive-usage bookkeeping); check() itself is empty
    engine_driven = True

    def check(self, module, ctx):
        return iter(())


# ---------------------------------------------------------------------------
# CLUSTER-ASSUME
# ---------------------------------------------------------------------------

#: env vars that hardcode process topology — reading them outside the
#: launcher/cluster seam bakes "the fleet I started with" into code
#: that must survive membership changes
_TOPOLOGY_ENV = {"APEX_TPU_NUM_PROCESSES", "APEX_TPU_PROCESS_ID"}


@register
class ClusterAssume(Rule):
    """Raw process-topology assumptions outside the cluster layer — PR 15.

    ``jax.process_index()`` / ``jax.process_count()`` answer "who am I
    in the fleet the job STARTED with".  Under the elastic cluster
    runtime that fleet is a moving target: a membership epoch can
    retire rank 3 while code still branches on ``process_index() != 0``
    — the incident was exactly that, a rank-0 gate in the amp logging
    path that picked a NEW rank 0 after a shrink and silently swapped
    which host wrote logs mid-run.  Topology questions go through the
    sanctioned seam (``parallel.distributed.rank/num_processes/
    init_distributed``) or key off an ``apex_tpu.cluster``
    MembershipView epoch, which is immutable per epoch by construction.
    """
    id = "CLUSTER-ASSUME"
    summary = "raw process-topology query outside the cluster layer"
    hint = ("route through apex_tpu.parallel.distributed (rank(), "
            "num_processes(), init_distributed()) or key off an "
            "apex_tpu.cluster MembershipView epoch — raw process ids "
            "go stale the moment cluster membership changes")

    _CALLS = {"jax.process_index": "jax.process_index() — raw rank "
                                   "query; stale after a membership "
                                   "change",
              "jax.process_count": "jax.process_count() — raw fleet "
                                   "size; stale after a membership "
                                   "change",
              "jax.distributed.initialize": "bare jax.distributed."
                                            "initialize — blocks "
                                            "forever with no retry; "
                                            "use init_distributed()"}

    def check(self, module, ctx):
        path = module.path.replace("\\", "/")
        if "apex_tpu/cluster/" in path or path.endswith(
                "apex_tpu/parallel/distributed.py"):
            return      # the sanctioned topology homes
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d in self._CALLS:
                    yield self.finding(module, node, self._CALLS[d])
                elif d == "os.environ.get" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value in _TOPOLOGY_ENV:
                    yield self.finding(
                        module, node,
                        f"os.environ.get({node.args[0].value!r}) — "
                        f"hardcoded process-count arithmetic outside "
                        f"the launcher seam")
            elif isinstance(node, ast.Subscript):
                if (_dotted(node.value) or "") == "os.environ" and \
                        isinstance(node.slice, ast.Constant) and \
                        node.slice.value in _TOPOLOGY_ENV:
                    yield self.finding(
                        module, node,
                        f"os.environ[{node.slice.value!r}] — hardcoded "
                        f"process-count arithmetic outside the "
                        f"launcher seam")


# ---------------------------------------------------------------------------
# WEIGHT-PUBLISH
# ---------------------------------------------------------------------------

#: identifier fragments that name model/optimizer state pytrees — the
#: things whose placement must stay measured (raw movement of a batch
#: named `images` or a telemetry leaf is fine)
_WEIGHTY = ("param", "master", "weight", "state")


@register
class WeightPublish(Rule):
    """Raw device placement of model-parameter pytrees — PR 18.

    ``jax.device_put`` / ``jax.device_get`` of weights outside the
    sanctioned seams is weight movement the runtime cannot see: it
    skips ``reshard_state``'s layout-identical zero-copy fast path, its
    dtype/shape validation, and the per-leaf hit stats every measured
    sync reports — the incident (docs/lint.md) was a rollout publish
    hand-rolled with ``device_get``+``device_put`` that silently
    gathered 100% of the masters to host every epoch and re-placed
    them, turning a zero-copy swap into the slowest stage of the loop.
    Weight movement goes through ``runtime/resilience.py`` (reshard /
    checkpoint), the ``parallel/`` placement layer, or the rollout
    publish path (``apex_tpu/rollout/publish.py``).
    """
    id = "WEIGHT-PUBLISH"
    summary = "raw device_put/device_get of a parameter pytree"
    hint = ("move weights through the measured surfaces — "
            "runtime.resilience.reshard_state (validated, zero-copy "
            "where layouts match, per-leaf stats) or "
            "rollout.WeightPublisher (cast-once, versioned, telemetered)"
            " — raw placement is invisible to the sync accounting")

    _CALLS = {"jax.device_put", "jax.device_get"}

    @staticmethod
    def _weighty_arg(arg: ast.AST) -> Optional[str]:
        """The first weight-ish identifier fragment in the arg subtree
        ('master_params', 'step.state', ...), else None."""
        for sub in ast.walk(arg):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is None:
                continue
            low = name.lower()
            if any(t in low for t in _WEIGHTY):
                return name
        return None

    def check(self, module, ctx):
        path = module.path.replace("\\", "/")
        if path.endswith("apex_tpu/runtime/resilience.py") \
                or "apex_tpu/parallel/" in path \
                or path.endswith("apex_tpu/rollout/publish.py"):
            return      # the sanctioned weight-movement homes
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            if d not in self._CALLS or not node.args:
                continue
            name = self._weighty_arg(node.args[0])
            if name is not None:
                yield self.finding(
                    module, node,
                    f"{d}({name}, ...) — raw placement of what looks "
                    f"like model/optimizer state; unmeasured weight "
                    f"movement bypasses the reshard surface")


# ---------------------------------------------------------------------------
# POOL-ALIAS
# ---------------------------------------------------------------------------

#: BlockPool bookkeeping attributes no code outside serve/pool.py may
#: touch — mutating them directly desynchronizes refcounts from block
#: tables, which the pool can only report as a leak or a double free
_POOL_PRIVATE_ATTRS = {"_free", "_refs", "_cached", "_hash_index",
                       "_block_hash"}

#: jnp ``.at[...]`` scatter methods that WRITE (``.get`` reads)
_AT_WRITE_METHODS = {"set", "add", "subtract", "multiply", "divide",
                     "min", "max", "apply", "power"}


def _names_a_pool(node: ast.AST) -> bool:
    """True when a dotted expression's name says it is a KV pool
    (``pool``, ``self.pool``, ``engine.dpool``, ``block_pool.q`` ...).
    Name-based on purpose: pool buffers are plain jnp arrays by the
    time they are scattered into, so there is no type to resolve —
    and the repo's naming convention is exactly what the rule audits."""
    d = _dotted(node)
    if d is None:
        return False
    return any("pool" in part.lower() for part in d.split("."))


@register
class PoolAlias(Rule):
    """Pool-block writes outside the refcount API — PR 20.

    The prefix cache made pool blocks SHARED: ``acquire_prefix`` hands
    N sessions the same physical block, ``commit`` publishes it in the
    hash index, and the only safe mutations are the pool's own
    refcounted verbs (``alloc`` / ``free`` / ``commit`` /
    ``acquire_prefix`` / ``flush_cache``).  Two aliasing hazards exist
    and both are silent at the write site.  (1) An in-place scatter
    (``pool.at[..., blk].set(...)``) into a shared block rewrites KV
    that OTHER sessions' attention is reading — cross-session
    corruption with no crash, just wrong tokens for whoever shares the
    prefix; every legitimate scatter lives in the serve kernel bodies
    (serve/kernels.py, including the copy-on-write fork) or the
    handoff restore (runtime/resilience.py), where the scheduler has
    proven the target block exclusive.  (2) Reaching into the pool's
    private bookkeeping (``pool._free`` / ``pool._refs`` / the hash
    index) instead of calling ``free`` bypasses refcounting entirely:
    a block two tables still reference returns to the free list, the
    allocator re-grants it, and two sessions now scatter into each
    other.  Flags both patterns on any pool-named base outside the
    sanctioned homes; docs/lint.md carries the incident.
    """
    id = "POOL-ALIAS"
    summary = ("direct free/scatter-write of KV pool blocks outside "
               "serve/pool.py's refcount API (shared-block corruption)")
    hint = ("go through the BlockPool verbs — alloc/free/commit/"
            "acquire_prefix keep refcounts and block tables in sync; "
            "a write into a shared block belongs behind a copy-on-write "
            "fork (scheduler cow_pending + the block_copy program), "
            "never an ad-hoc scatter; see docs/serving.md's Prefix "
            "caching section")

    def check(self, module, ctx):
        path = module.path.replace("\\", "/")
        if path.endswith("apex_tpu/serve/pool.py"):
            return      # the refcount API's own implementation
        kernel_home = path.endswith("apex_tpu/serve/kernels.py") \
            or path.endswith("apex_tpu/runtime/resilience.py")
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr in _POOL_PRIVATE_ATTRS and \
                    _names_a_pool(node.value):
                yield self.finding(
                    module, node,
                    f"direct access to pool bookkeeping "
                    f"'.{node.attr}' — mutating it desynchronizes "
                    f"refcounts from live block tables (double grants "
                    f"of shared blocks)")
                continue
            if kernel_home or not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _AT_WRITE_METHODS
                    and isinstance(fn.value, ast.Subscript)):
                continue
            at = fn.value.value
            if isinstance(at, ast.Attribute) and at.attr == "at" and \
                    _names_a_pool(at.value):
                yield self.finding(
                    module, node,
                    f"in-place .at[...].{fn.attr}() scatter into a KV "
                    f"pool buffer — if the target block is shared "
                    f"(prefix cache), this rewrites KV other sessions "
                    f"are reading")
