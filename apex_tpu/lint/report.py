"""Reporters: human-readable (default) and JSON (tooling/CI)."""
from __future__ import annotations

import json


def human(result, show_suppressed=False) -> str:
    lines = []
    shown = [f for f in result.findings
             if show_suppressed or not (f.suppressed or f.baselined)]
    for f in shown:
        lines.append(f.format())
    c = result.counts()
    tail = (f"{c['findings']} finding(s), {c['suppressed']} suppressed, "
            f"{c['baselined']} baselined — {c['files']} files, "
            f"{len(c['rules_run'])} rules, {c['lint_ms']:.0f} ms")
    lines.append(tail)
    return "\n".join(lines)


def as_json(result, show_suppressed=False) -> str:
    out = result.counts()
    out["findings_list"] = [
        f.to_json() for f in result.findings
        if show_suppressed or not (f.suppressed or f.baselined)]
    return json.dumps(out, indent=1)


def as_sarif(result) -> str:
    """SARIF 2.1.0 — the interchange schema GitHub code scanning and
    most editors ingest.  Only live findings are emitted (suppressed and
    baselined ones are this tool's own bookkeeping)."""
    from . import rules as _rules

    driver_rules = [
        {"id": rid,
         "shortDescription": {"text": _rules.REGISTRY[rid].summary}}
        for rid in _rules.rule_ids()]
    results = []
    for f in result.active():
        text = f.message if not f.hint else f"{f.message} ({f.hint})"
        results.append({
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": text},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/")},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
            }],
        })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "apex-tpu-lint",
                                "rules": driver_rules}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1)
