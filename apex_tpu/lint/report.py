"""Reporters: human-readable (default) and JSON (tooling/CI)."""
from __future__ import annotations

import json


def human(result, show_suppressed=False) -> str:
    lines = []
    shown = [f for f in result.findings
             if show_suppressed or not (f.suppressed or f.baselined)]
    for f in shown:
        lines.append(f.format())
    c = result.counts()
    tail = (f"{c['findings']} finding(s), {c['suppressed']} suppressed, "
            f"{c['baselined']} baselined — {c['files']} files, "
            f"{len(c['rules_run'])} rules, {c['lint_ms']:.0f} ms")
    lines.append(tail)
    return "\n".join(lines)


def as_json(result, show_suppressed=False) -> str:
    out = result.counts()
    out["findings_list"] = [
        f.to_json() for f in result.findings
        if show_suppressed or not (f.suppressed or f.baselined)]
    return json.dumps(out, indent=1)
