"""jaxpr-level program verifier: trace the library's REAL entry programs
and check invariants on the IR itself.

The AST rules in :mod:`.rules` judge source; this module judges what jax
actually stages.  It runs tiny CPU workloads through the same entry
points production uses — the fused train step (``make_train_step``),
the eager optimizer executor (``FusedAdam.step``), the serving engine
(``ServeEngine.run``), and every registered kernel's BOTH tiers — then
audits the resulting jaxprs:

* **no-callbacks** — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` never appear in a train or serve program (a
  callback is a hidden host round-trip per dispatch; the IR-level twin
  of HOST-SYNC).
* **scan-collective** — collectives sit at scan boundaries, never
  inside a scan body (the jaxpr-level SCAN-COLLECTIVE; ``ppermute``
  pipeline rotations are exempt, matching the AST rule).
* **scan-carry-fp32** — no fp16/bf16 floating carry in a train-step
  ``lax.scan``: gradient windows accumulate in fp32 (integer and key
  carries are fine; it is HALF accumulators that silently lose mantissa
  over a window).
* **donation-census** — with the donation policy forced on, the lowered
  HLO of donated programs aliases input buffers to outputs
  (``tf.aliasing_output``), generalizing
  tests/test_executor.py::test_donation_alias_in_lowered_hlo to every
  cached program of a donated kind.
* **telemetry-carry** — turning ``telemetry=True`` grows the train
  step's jaxpr by EXACTLY the telemetry carry leaves, on both the input
  and the output side: observability rides the state carry and adds
  zero extra outputs (the zero-dispatch contract of apex_tpu.observe).

Programs are collected once per process (memoized) — the audit traces
abstractly where it can and runs one tiny concrete step where the
program cache is populated by execution.  Exposed as
``python -m apex_tpu.lint --jaxpr`` and the tier-1 gate in
tests/test_jaxpr_audit.py.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, List, Optional, Sequence, Tuple

# must win before the first jax backend lookup: the audit traces on CPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: primitives that smuggle a host call into a compiled program
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
})

#: cross-device primitives whose placement the scan rule polices
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter",
})

#: exempt inside scans: pipeline/ring rotations are per-iteration by
#: design (mirrors rules.ScanCollective._rotation_only)
ROTATION_PRIMS = frozenset({"ppermute", "pshuffle"})

HALF_DTYPES = ("float16", "bfloat16")

#: kinds compiled under the donation policy whose lowered HLO must
#: alias at least one input buffer to an output
DONATED_KINDS = frozenset({"fused_adam", "fused_sgd", "train_step"})


# ---------------------------------------------------------------------------
# result model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Check:
    name: str
    ok: bool
    detail: str = ""


@dataclasses.dataclass
class ProgramReport:
    name: str                      # display name, e.g. "train_step[telemetry]"
    kind: str                      # step_cache kind or "kernel.<name>.<tier>"
    checks: List[Check] = dataclasses.field(default_factory=list)

    @property
    def passed(self) -> bool:
        return all(c.ok for c in self.checks)


class AuditResult:
    def __init__(self):
        self.programs: List[ProgramReport] = []
        self.errors: List[str] = []
        self.elapsed_ms: float = 0.0

    @property
    def passed(self) -> bool:
        return not self.errors and all(p.passed for p in self.programs)

    def failures(self) -> List[Tuple[str, Check]]:
        return [(p.name, c) for p in self.programs for c in p.checks
                if not c.ok]

    def counts(self) -> dict:
        return {
            "jaxpr_audit_ms": round(self.elapsed_ms, 1),
            "programs_audited": len(self.programs),
            "checks_run": sum(len(p.checks) for p in self.programs),
            "failures": len(self.failures()) + len(self.errors),
        }

    def format(self, verbose: bool = False) -> str:
        lines = []
        for p in self.programs:
            mark = "ok" if p.passed else "FAIL"
            lines.append(f"  [{mark:>4}] {p.name}  "
                         f"({len(p.checks)} checks)")
            for c in p.checks:
                if not c.ok:
                    lines.append(f"         - {c.name}: {c.detail}")
                elif verbose:
                    lines.append(f"         + {c.name}"
                                 + (f": {c.detail}" if c.detail else ""))
        for e in self.errors:
            lines.append(f"  [FAIL] audit error: {e}")
        n_fail = len(self.failures()) + len(self.errors)
        lines.append(
            f"jaxpr audit: {len(self.programs)} program(s), "
            f"{sum(len(p.checks) for p in self.programs)} check(s), "
            f"{n_fail} failure(s), ~{self.elapsed_ms / 1000.0:.1f}s")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _sub_jaxprs(params: dict):
    """Inner jaxprs of one eqn's params, whatever the spelling
    (pjit's ``jaxpr``, scan/while's ``jaxpr``/``cond_jaxpr``/
    ``body_jaxpr``, cond's ``branches``, custom_*'s callables are
    skipped — they retrace, they are not staged IR)."""
    for key, val in params.items():
        for item in (val if isinstance(val, (tuple, list)) else (val,)):
            jx = getattr(item, "jaxpr", None)
            if jx is not None and hasattr(jx, "eqns"):
                yield key, jx
            elif hasattr(item, "eqns"):
                yield key, item


def walk_eqns(jaxpr, scan_depth: int = 0):
    """Yield ``(eqn, scan_depth)`` over every eqn of ``jaxpr`` and its
    staged sub-jaxprs; ``scan_depth`` counts enclosing scan/while
    bodies."""
    for eqn in jaxpr.eqns:
        yield eqn, scan_depth
        is_loop = eqn.primitive.name in ("scan", "while")
        for _, sub in _sub_jaxprs(eqn.params):
            yield from walk_eqns(sub, scan_depth + (1 if is_loop else 0))


def iter_scans(jaxpr):
    """Yield every ``scan`` eqn in ``jaxpr`` (recursively)."""
    for eqn, _ in walk_eqns(jaxpr):
        if eqn.primitive.name == "scan":
            yield eqn


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------


def check_no_callbacks(jaxpr) -> Check:
    hits = sorted({eqn.primitive.name for eqn, _ in walk_eqns(jaxpr)
                   if eqn.primitive.name in CALLBACK_PRIMS})
    return Check(
        "no-callbacks", not hits,
        f"host callback primitive(s) staged into the program: {hits}"
        if hits else "no callback primitives")


def check_scan_collectives(jaxpr) -> Check:
    bad = sorted({eqn.primitive.name for eqn, depth in walk_eqns(jaxpr)
                  if depth > 0 and eqn.primitive.name in COLLECTIVE_PRIMS})
    return Check(
        "scan-collective", not bad,
        f"collective(s) inside a scan body: {bad} — hoist to the scan "
        f"boundary (accumulate locally, reduce once)"
        if bad else "collectives only at scan boundaries")


def check_scan_carries_fp32(jaxpr) -> Check:
    """No half-precision FLOATING carry in any scan: window accumulators
    must be fp32 (rng keys / ints / bools pass through untouched)."""
    bad = []
    n_scans = 0
    for eqn in iter_scans(jaxpr):
        n_scans += 1
        num_carry = eqn.params.get("num_carry", 0)
        inner = eqn.params["jaxpr"].jaxpr
        num_consts = eqn.params.get("num_consts", 0)
        carries = inner.invars[num_consts:num_consts + num_carry]
        for i, v in enumerate(carries):
            dt = str(getattr(v.aval, "dtype", ""))
            if dt in HALF_DTYPES:
                bad.append(f"carry[{i}]:{dt}{getattr(v.aval, 'shape', ())}")
    return Check(
        "scan-carry-fp32", not bad,
        f"half-precision scan carries (accumulate in fp32): {bad}"
        if bad else f"{n_scans} scan(s), all floating carries fp32")


def check_donation(entry) -> Check:
    """Lowered-HLO donation census: a program of a donated kind traced
    under ``donation.set(True)`` must alias inputs to outputs."""
    txt = entry["fn"].lower(*entry["example"]).as_text()
    n = txt.count("tf.aliasing_output")
    return Check(
        "donation-census", n >= 1,
        f"{n} aliased buffer(s)" if n else
        "no tf.aliasing_output in lowered HLO despite donation forced on")


def check_telemetry_carry(closed_off, closed_on, n_leaves: int) -> Check:
    """Telemetry grows the step by exactly its carry leaves, in == out:
    zero extra outputs beyond the carried accumulator itself."""
    d_in = len(closed_on.jaxpr.invars) - len(closed_off.jaxpr.invars)
    d_out = len(closed_on.jaxpr.outvars) - len(closed_off.jaxpr.outvars)
    ok = d_in == d_out == n_leaves
    return Check(
        "telemetry-carry", ok,
        f"telemetry=True delta: +{d_in} inputs / +{d_out} outputs "
        f"(expected +{n_leaves}/+{n_leaves}: the StepTelemetry leaves "
        f"ride the state carry, nothing else)" if not ok else
        f"+{n_leaves} in / +{n_leaves} out, zero extra")


# ---------------------------------------------------------------------------
# program providers (tiny real workloads, CPU)
# ---------------------------------------------------------------------------


def _entries_after(n0: int):
    from apex_tpu.runtime import step_cache as sc
    return sc.step_cache.entries()[n0:]


def _n_entries() -> int:
    from apex_tpu.runtime import step_cache as sc
    return len(sc.step_cache.entries())


def _train_workload(telemetry: bool):
    """One optimizer window of the fused train step: 2-microbatch grad
    accumulation (so the program HAS a scan) in fp16 AMP."""
    import jax.numpy as jnp
    import numpy as np

    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    nn.manual_seed(11)
    model = nn.Sequential(nn.Linear(6, 5), nn.ReLU(), nn.Linear(5, 3))
    opt = FusedSGD(list(model.parameters()), lr=0.05, momentum=0.9)
    step = make_train_step(model, opt,
                           lambda o, y: F.cross_entropy(o, y),
                           half_dtype=jnp.float16,
                           grad_accum_steps=2,
                           telemetry=telemetry)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 3, size=(4,)), jnp.int32)
    step(x, y)


def _optimizer_workload():
    """The eager executor surface: FusedAdam.step() over two parameter
    shapes (the test_executor donation-census workload, miniaturized)."""
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.nn import Parameter
    from apex_tpu.optimizers import FusedAdam

    rng = np.random.default_rng(5)
    params = []
    for s in [(9,), (4, 3)]:
        p = Parameter(jnp.asarray(rng.standard_normal(s), jnp.float32))
        p.grad = jnp.asarray(rng.standard_normal(s), jnp.float32)
        params.append(p)
    opt = FusedAdam(params, lr=1e-2)
    opt.step()


def _serve_workload():
    """Prefill + decode through the continuous-batching engine on a
    2-layer toy LM — populates the prefill_step/decode_step kinds."""
    import apex_tpu.nn as nn
    from apex_tpu.models.gpt import GptModel
    from apex_tpu.serve import Request, ServeEngine

    nn.manual_seed(13)
    model = GptModel(vocab_size=41, hidden=24, layers=1, heads=2,
                     max_positions=48, dropout=0.0, attn_dropout=0.0)
    model.eval()
    eng = ServeEngine(model, num_blocks=24, block_size=4, max_batch=2)
    eng.run([Request("a", [3, 7, 5], 3), Request("b", [9, 2], 3)])


def _trace_entry(entry):
    import jax
    return jax.make_jaxpr(lambda *a: entry["fn"](*a))(*entry["example"])


def _audit_entry(entry, *, name=None, donated=False,
                 scan_carries=False) -> ProgramReport:
    rep = ProgramReport(name=name or entry["kind"], kind=entry["kind"])
    try:
        closed = _trace_entry(entry)
    except Exception as exc:           # noqa: BLE001 — report, don't crash
        rep.checks.append(Check("trace", False,
                                f"{type(exc).__name__}: {exc}"))
        return rep
    rep.checks.append(check_no_callbacks(closed.jaxpr))
    rep.checks.append(check_scan_collectives(closed.jaxpr))
    if scan_carries:
        rep.checks.append(check_scan_carries_fp32(closed.jaxpr))
    if donated:
        try:
            rep.checks.append(check_donation(entry))
        except Exception as exc:       # noqa: BLE001
            rep.checks.append(Check("donation-census", False,
                                    f"{type(exc).__name__}: {exc}"))
    return rep


def _kernel_reports() -> List[ProgramReport]:
    """Both tiers of every registered kernel, traced abstractly from the
    spec's ``audit_programs`` hook (tier label, callable, example
    avals)."""
    import jax

    import apex_tpu.kernels  # noqa: F401 — registration side effects
    from apex_tpu.kernels.dispatch import catalog

    out = []
    for kname in sorted(catalog()):
        spec = catalog()[kname]
        hook = getattr(spec, "audit_programs", None)
        rep_name = f"kernel.{kname}"
        if hook is None:
            rep = ProgramReport(name=rep_name, kind=rep_name)
            rep.checks.append(Check(
                "audit-hook", False,
                "registered kernel declares no audit_programs hook — "
                "both tiers must be traceable by the verifier"))
            out.append(rep)
            continue
        tiers = set()
        for tier, fn, example in hook():
            tiers.add(tier)
            rep = ProgramReport(name=f"{rep_name}.{tier}",
                                kind=f"kernel.{kname}.{tier}")
            try:
                closed = jax.make_jaxpr(fn)(*example)
            except Exception as exc:   # noqa: BLE001
                rep.checks.append(Check("trace", False,
                                        f"{type(exc).__name__}: {exc}"))
                out.append(rep)
                continue
            rep.checks.append(check_no_callbacks(closed.jaxpr))
            rep.checks.append(check_scan_collectives(closed.jaxpr))
            out.append(rep)
        if not {"pallas", "xla"} <= tiers:
            rep = ProgramReport(name=rep_name, kind=rep_name)
            rep.checks.append(Check(
                "both-tiers", False,
                f"audit hook covers tiers {sorted(tiers)}; need both "
                f"'pallas' and 'xla'"))
            out.append(rep)
    return out


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

_RESULT: Optional[AuditResult] = None


def run(force: bool = False) -> AuditResult:
    """Collect and audit every entry program; memoized per process."""
    global _RESULT
    if _RESULT is not None and not force:
        return _RESULT
    res = AuditResult()
    t0 = time.perf_counter()
    try:
        _run_into(res)
    except Exception as exc:           # noqa: BLE001 — an audit that
        # cannot even set up is a failing audit, not a crash of the CLI
        res.errors.append(f"{type(exc).__name__}: {exc}")
    res.elapsed_ms = (time.perf_counter() - t0) * 1000.0
    _RESULT = res
    return res


def _run_into(res: AuditResult) -> None:
    import jax

    from apex_tpu.observe.telemetry import init_telemetry
    from apex_tpu.runtime import executor as rex

    # train + eager-optimizer programs trace under forced donation so
    # the census sees the aliasing the accelerator path compiles with
    rex.donation.set(True)
    try:
        n0 = _n_entries()
        _train_workload(telemetry=False)
        train_off = _entries_after(n0)

        n1 = _n_entries()
        _train_workload(telemetry=True)
        train_on = _entries_after(n1)

        n2 = _n_entries()
        _optimizer_workload()
        opt_entries = _entries_after(n2)
    finally:
        rex.donation.set("auto")

    n3 = _n_entries()
    _serve_workload()
    serve_entries = _entries_after(n3)

    for e in train_off:
        res.programs.append(_audit_entry(
            e, donated=e["kind"] in DONATED_KINDS, scan_carries=True))
    for e in train_on:
        res.programs.append(_audit_entry(
            e, name=f"{e['kind']}[telemetry]",
            donated=e["kind"] in DONATED_KINDS, scan_carries=True))
    for e in opt_entries:
        res.programs.append(_audit_entry(
            e, donated=e["kind"] in DONATED_KINDS))
    for e in serve_entries:
        res.programs.append(_audit_entry(e))

    # telemetry-carry: the two train_step programs, off vs on
    base = [e for e in train_off if e["kind"] == "train_step"]
    tele = [e for e in train_on if e["kind"] == "train_step"]
    rep = ProgramReport(name="train_step[telemetry-delta]",
                        kind="train_step")
    if len(base) == 1 and len(tele) == 1:
        n_leaves = len(jax.tree_util.tree_leaves(init_telemetry()))
        try:
            rep.checks.append(check_telemetry_carry(
                _trace_entry(base[0]), _trace_entry(tele[0]), n_leaves))
        except Exception as exc:       # noqa: BLE001
            rep.checks.append(Check("telemetry-carry", False,
                                    f"{type(exc).__name__}: {exc}"))
    else:
        rep.checks.append(Check(
            "telemetry-carry", False,
            f"expected exactly one train_step per telemetry mode, got "
            f"{len(base)} off / {len(tele)} on"))
    res.programs.append(rep)

    res.programs.extend(_kernel_reports())
