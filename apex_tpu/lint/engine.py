"""Analyzer core: file discovery, AST parsing, suppressions, baseline,
and the run loop that drives the rule registry.

Pure stdlib — parsing a tree of a few hundred files plus running every
rule stays well under the tier-1 gate's 10s budget because nothing here
touches jax; the rules reason about *source text*, not live programs.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

#: the checked-in grandfather file shipped with the package; findings
#: fingerprinted here are reported as ``baselined`` and do not fail the
#: CLI / the tier-1 gate.  Regenerate with ``--write-baseline``.
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_\-*]+(?:\s*,\s*[A-Za-z0-9_\-*]+)*)\s*(.*)$")


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str               # as reported (relative to the lint root)
    line: int
    col: int
    message: str
    hint: str = ""
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False

    def format(self) -> str:
        state = ""
        if self.suppressed:
            state = f" [suppressed: {self.suppress_reason or 'no reason'}]"
        elif self.baselined:
            state = " [baselined]"
        out = f"{self.path}:{self.line}:{self.col}: {self.rule} " \
              f"{self.message}{state}"
        if self.hint and not (self.suppressed or self.baselined):
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Module:
    """One parsed source file plus its suppression table."""
    path: str               # absolute
    relpath: str            # posix-style, relative to the lint root
    source: str
    tree: ast.Module
    lines: List[str]
    # line -> [(rule-id-or-*, reason)]
    suppressions: Dict[int, List[Tuple[str, str]]]
    file_suppressions: List[Tuple[str, str]]
    dotted: Optional[str]   # best-effort dotted module name
    in_apex_package: bool
    sig: Tuple[int, int] = (0, 0)   # (mtime_ns, size) — cache identity

    def suppression_for(self, rule: str, line: int):
        """The (rule, reason) suppressing ``rule`` at ``line``: a
        file-wide directive, a directive on the flagged line itself, or
        one anywhere in the contiguous comment-only block directly above
        it (so reasons can wrap) — else None."""
        for ent in self.file_suppressions:
            if ent[0] in ("*", rule):
                return ent
        cand = line
        while cand == line or self._comment_only(cand):
            for ent in self.suppressions.get(cand, ()):
                if ent[0] in ("*", rule):
                    return ent
            cand -= 1
            if cand < 1:
                break
        return None

    def _comment_only(self, line: int) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        return self.lines[line - 1].lstrip().startswith("#")


class LintResult:
    """Everything one analyzer run produced.

    ``findings`` carries every finding including suppressed/baselined
    ones (reporters show them on request); :meth:`active` is the set
    that fails a build.  ``files`` is the full scanned set — the
    walk-coverage guarantee tests assert membership against it.
    """

    def __init__(self, findings, files, rules, elapsed_s,
                 dataflow_ms=0.0):
        self.findings: List[Finding] = findings
        self.files: List[str] = files
        self.rules: List[str] = rules
        self.elapsed_s: float = elapsed_s
        self.dataflow_ms: float = dataflow_ms

    def active(self) -> List[Finding]:
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    def counts(self) -> dict:
        return {
            "findings": len(self.active()),
            "suppressed": sum(1 for f in self.findings if f.suppressed),
            "baselined": sum(1 for f in self.findings if f.baselined),
            "stale_suppressions": sum(
                1 for f in self.findings
                if f.rule == "STALE-SUPPRESSION"),
            "files": len(self.files),
            "rules_run": list(self.rules),
            "lint_ms": round(self.elapsed_s * 1000.0, 2),
            "dataflow_ms": round(self.dataflow_ms, 2),
        }


def iter_py_files(paths: Iterable[str]):
    """Yield every .py file under ``paths`` (files pass through),
    skipping __pycache__, hidden directories, and build trees."""
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
                and d not in ("build", "dist"))
            for f in sorted(filenames):
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def _parse_suppressions(source: str):
    per_line: Dict[int, List[Tuple[str, str]]] = {}
    file_wide: List[Tuple[str, str]] = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, ids, reason = m.group(1), m.group(2), m.group(3).strip()
        for rid in (s.strip() for s in ids.split(",")):
            ent = (rid, reason)
            if kind == "disable-file":
                file_wide.append(ent)
            else:
                per_line.setdefault(i, []).append(ent)
    return per_line, file_wide


def _dotted_name(path: str) -> Optional[str]:
    """Best-effort dotted module name: climb while __init__.py exists."""
    path = os.path.abspath(path)
    base = os.path.basename(path)
    parts = [] if base == "__init__.py" else [base[:-3]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) if parts else None


#: abspath -> ((mtime_ns, size), parse payload).  The AST objects are
#: SHARED across runs (node identity is what lets the analysis cache
#: reuse a callgraph/dataflow built from the same trees); Module shells
#: are rebuilt per run because relpath depends on the lint root.
_PARSE_CACHE: Dict[str, Tuple[Tuple[int, int], tuple]] = {}


def _file_sig(path: str) -> Tuple[int, int]:
    st = os.stat(path)
    return (st.st_mtime_ns, st.st_size)


def load_module(path: str, root: str):
    """Parse one file (mtime+size cached).  Returns (Module, None) or
    (None, Finding) when the file does not parse — a PARSE-ERROR is
    itself a finding (a file the analyzer cannot read is a file it
    cannot vouch for)."""
    abspath = os.path.abspath(path)
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        sig = _file_sig(abspath)
    except OSError as e:
        return None, Finding("PARSE-ERROR", relpath, 1, 0,
                             f"could not parse: {e}")
    cached = _PARSE_CACHE.get(abspath)
    if cached is not None and cached[0] == sig:
        payload = cached[1]
    else:
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=abspath)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            line = getattr(e, "lineno", 1) or 1
            return None, Finding("PARSE-ERROR", relpath, line, 0,
                                 f"could not parse: {e}")
        per_line, file_wide = _parse_suppressions(source)
        payload = (source, tree, source.splitlines(), per_line,
                   file_wide, _dotted_name(abspath))
        _PARSE_CACHE[abspath] = (sig, payload)
    source, tree, lines, per_line, file_wide, dotted = payload
    parts = abspath.replace(os.sep, "/").split("/")
    return Module(
        path=abspath, relpath=relpath, source=source,
        tree=tree, lines=lines, suppressions=per_line,
        file_suppressions=file_wide, dotted=dotted,
        in_apex_package="apex_tpu" in parts, sig=sig), None


# -- baseline ---------------------------------------------------------------
#
# A baselined finding is matched by CONTENT fingerprint — (rule, path,
# stripped source line text, k-th occurrence of that triple) — so pure
# line-number drift (edits above the finding) does not un-baseline it,
# while touching the flagged line itself does.


def _fingerprint(f: Finding, text: str, k: int) -> str:
    return f"{f.rule}::{f.path}::{text.strip()}::{k}"


def _finding_fingerprints(findings, modules_by_rel):
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        mod = modules_by_rel.get(f.path)
        text = ""
        if mod is not None and 1 <= f.line <= len(mod.lines):
            text = mod.lines[f.line - 1]
        key = (f.rule, f.path, text.strip())
        k = seen.get(key, 0)
        seen[key] = k + 1
        out.append(_fingerprint(f, text, k))
    return out


def load_baseline(path: Optional[str]):
    if not path or not os.path.isfile(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return set(data.get("findings", []))


def write_baseline(path: str, result: "LintResult", modules_by_rel) -> int:
    """Write every currently-unsuppressed finding as the new baseline;
    returns the number grandfathered."""
    fps = _finding_fingerprints(
        [f for f in result.findings if not f.suppressed],
        modules_by_rel)
    payload = {"version": 1, "tool": "apex_tpu.lint",
               "findings": sorted(fps)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return len(fps)


# -- run loop ---------------------------------------------------------------

#: frozenset((abspath, sig)) -> {"callgraph", "dataflow"} — LRU.  The
#: callgraph/dataflow fixpoint is the expensive half of a deep lint;
#: repeated runs over an unchanged tree (tests, watch loops, bench
#: repeats) reuse both because the parse cache hands back the same ASTs.
_ANALYSIS_CACHE: "OrderedDict[frozenset, dict]" = OrderedDict()
_ANALYSIS_CACHE_MAX = 8


def _analysis_for(modules):
    from .callgraph import CallGraph
    key = frozenset((m.path, m.sig) for m in modules)
    entry = _ANALYSIS_CACHE.get(key)
    if entry is None:
        entry = {"callgraph": CallGraph(modules), "dataflow": None}
        _ANALYSIS_CACHE[key] = entry
        while len(_ANALYSIS_CACHE) > _ANALYSIS_CACHE_MAX:
            _ANALYSIS_CACHE.popitem(last=False)
    else:
        _ANALYSIS_CACHE.move_to_end(key)
    return entry


def _stale_pass(modules, used, judged, all_judged, ctx):
    """STALE-SUPPRESSION: directives naming a judged rule that matched
    no finding this run.  ``used`` holds id()s of the (rule, reason)
    entries some finding consumed; a ``*`` directive is only judged
    when the full registry ran.  A reachability-scoped rule (HOST-SYNC,
    OBS-IN-JIT, the dataflow rules) judges a line only when it sits
    inside a traced-REACHABLE function in THIS scan's scope — outside
    that span its directives are unjudged, not stale."""
    registry = _rules_registry()
    for mod in modules:
        spans = None
        sites = [(line, ent) for line, ents in mod.suppressions.items()
                 for ent in ents]
        sites += [(1, ent) for ent in mod.file_suppressions]
        for line, ent in sorted(sites, key=lambda s: s[0]):
            rid = ent[0]
            if id(ent) in used:
                continue
            if rid == "*":
                if not all_judged:
                    continue
            elif rid not in judged:
                continue
            rule = registry.get(rid)
            if rule is not None and rule.reachability_scoped:
                if spans is None:
                    spans = [
                        (i.node.lineno,
                         getattr(i.node, "end_lineno", i.node.lineno))
                        for i in
                        ctx.callgraph.reachable_functions(mod.path)]
                in_span = any(lo <= line <= hi for lo, hi in spans)
                if not in_span and line != 1:
                    continue
                if line == 1 and not spans:   # file-wide directive
                    continue
            f = Finding(
                "STALE-SUPPRESSION", mod.relpath, line, 0,
                f"suppression `disable={rid}` matches no {rid} "
                f"finding — the analyzer proves this site clean; the "
                f"directive now only masks future regressions",
                _rules_registry()["STALE-SUPPRESSION"].hint)
            sup = mod.suppression_for(f.rule, f.line)
            if sup is not None and id(sup) != id(ent):
                f.suppressed = True
                f.suppress_reason = sup[1]
            yield f


def _rules_registry():
    from . import rules as _rules
    return _rules.REGISTRY


def run(paths, select=None, ignore=None, baseline=DEFAULT_BASELINE,
        root=None):
    """Run the rule registry over ``paths``.

    ``select`` / ``ignore`` are iterables of rule ids; ``baseline`` a
    path (or None to disable).  ``root`` anchors reported relative paths
    and baseline fingerprints (default: cwd).  Returns a
    :class:`LintResult`; the caller decides what exit status
    ``result.active()`` maps to.
    """
    from . import rules as _rules

    t0 = time.perf_counter()
    root = os.path.abspath(root or os.getcwd())
    active_rules = _rules.resolve(select, ignore)

    modules: List[Module] = []
    findings: List[Finding] = []
    files: List[str] = []
    for path in iter_py_files(paths):
        files.append(os.path.abspath(path))
        mod, err = load_module(path, root)
        if err is not None:
            findings.append(err)
        else:
            modules.append(mod)

    analysis = _analysis_for(modules)
    ctx = _rules.LintContext(modules=modules,
                             callgraph=analysis["callgraph"],
                             dataflow=lambda: _cached_dataflow(
                                 analysis, modules))
    used = set()                      # id(ent) of consumed directives
    for rule in active_rules:
        for mod in modules:
            for f in rule.check(mod, ctx):
                ent = mod.suppression_for(f.rule, f.line)
                if ent is not None:
                    f.suppressed = True
                    f.suppress_reason = ent[1]
                    used.add(id(ent))
                findings.append(f)

    active_ids = {r.id for r in active_rules}
    if "STALE-SUPPRESSION" in active_ids:
        # shadow pass: rules NOT selected still get to claim their
        # directives (their findings are discarded), so a narrow
        # `--select STALE-SUPPRESSION` run judges every directive the
        # registry can judge rather than calling them all stale
        judged = set(active_ids)
        for rule in _rules.REGISTRY.values():
            if rule.id in active_ids or \
                    getattr(rule, "engine_driven", False):
                continue
            judged.add(rule.id)
            for mod in modules:
                for f in rule.check(mod, ctx):
                    ent = mod.suppression_for(f.rule, f.line)
                    if ent is not None:
                        used.add(id(ent))
        all_judged = judged >= set(_rules.REGISTRY) - {"STALE-SUPPRESSION"}
        stale = list(_stale_pass(modules, used, judged, all_judged, ctx))
        findings.extend(stale)

    by_rel = {m.relpath: m for m in modules}
    baselined = load_baseline(baseline)
    if baselined:
        for f, fp in zip(findings,
                         _finding_fingerprints(findings, by_rel)):
            if not f.suppressed and fp in baselined:
                f.baselined = True

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result = LintResult(findings, files,
                        [r.id for r in active_rules],
                        time.perf_counter() - t0,
                        dataflow_ms=ctx.dataflow_ms)
    result._modules_by_rel = by_rel      # for --write-baseline
    return result


def _cached_dataflow(analysis, modules):
    if analysis["dataflow"] is None:
        from . import dataflow as _df
        analysis["dataflow"] = _df.build(modules, analysis["callgraph"])
    return analysis["dataflow"]
