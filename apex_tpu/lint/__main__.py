"""``python -m apex_tpu.lint`` / the ``apex-tpu-lint`` console script.

Exit-code contract (stable; CI keys off it):
  0 = clean — no unsuppressed, non-baselined findings (with ``--jaxpr``:
      every audited program passed every check),
  1 = findings — live lint findings, files that failed to parse, or
      (with ``--jaxpr``) at least one failing program check,
  2 = usage error — unknown rule id, missing path, or git failure
      under ``--changed``.  Nothing is written to stdout on exit 2.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

from . import engine, report, rules


def _changed_files(base: str):
    """Python files touched relative to ``base`` (plus untracked ones) —
    the ``git diff`` scope for incremental lint runs."""
    diff = subprocess.run(
        ["git", "diff", "--name-only", base, "--", "*.py"],
        capture_output=True, text=True, check=True)
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard", "--", "*.py"],
        capture_output=True, text=True, check=True)
    names = set(diff.stdout.splitlines()) | set(untracked.stdout.splitlines())
    return sorted(p for p in names if os.path.exists(p))


def _run_jaxpr_audit(fmt: str) -> int:
    from . import jaxpr_audit
    res = jaxpr_audit.run()
    if fmt == "json":
        import json
        out = res.counts()
        out["programs"] = [
            {"name": p.name, "kind": p.kind, "passed": p.passed,
             "checks": [{"name": c.name, "ok": c.ok, "detail": c.detail}
                        for c in p.checks]}
            for p in res.programs]
        out["errors"] = res.errors
        print(json.dumps(out, indent=1))
    else:
        print(res.format(verbose=fmt == "human"))
    return 0 if res.passed else 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="apex-tpu-lint",
        description="AST-based TPU-hazard analyzer (rule catalog: "
                    "docs/lint.md); --jaxpr runs the jaxpr-level "
                    "program verifier instead")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: apex_tpu "
                         "and examples under the cwd, else the cwd)")
    ap.add_argument("--format", choices=["human", "json", "sarif"],
                    default="human")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", default=None,
                    help="comma-separated rule ids to skip")
    ap.add_argument("--baseline", default=engine.DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings "
                         "(default: the checked-in package baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as live")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into "
                         "--baseline and exit 0")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed/baselined findings")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only python files changed vs REF "
                         "(default HEAD) plus untracked ones, instead "
                         "of the positional paths")
    ap.add_argument("--jaxpr", action="store_true",
                    help="run the jaxpr-level program verifier (traces "
                         "the real train/serve/kernel entry programs "
                         "on CPU and audits the IR) instead of the "
                         "AST rules")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in rules.rule_ids():
            r = rules.REGISTRY[rid]
            print(f"{rid}: {r.summary}")
        return 0

    if args.jaxpr:
        return _run_jaxpr_audit(args.format)

    if args.changed is not None:
        try:
            paths = _changed_files(args.changed)
        except (subprocess.CalledProcessError, OSError) as e:
            err = getattr(e, "stderr", "") or str(e)
            print(f"apex-tpu-lint: --changed failed: {err.strip()}",
                  file=sys.stderr)
            return 2
        if not paths:
            print("apex-tpu-lint: no changed python files")
            return 0
    else:
        paths = args.paths
        if not paths:
            paths = [p for p in ("apex_tpu", "examples") if os.path.isdir(p)]
            if not paths:
                paths = ["."]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"apex-tpu-lint: no such path(s): {missing}",
                  file=sys.stderr)
            return 2

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    baseline = None if args.no_baseline else args.baseline
    try:
        result = engine.run(paths, select=select, ignore=ignore,
                            baseline=baseline)
    except KeyError as e:
        print(f"apex-tpu-lint: {e.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        n = engine.write_baseline(args.baseline, result,
                                  result._modules_by_rel)
        print(f"apex-tpu-lint: baselined {n} finding(s) -> "
              f"{args.baseline}")
        return 0

    if args.format == "json":
        print(report.as_json(result, args.show_suppressed))
    elif args.format == "sarif":
        print(report.as_sarif(result))
    else:
        print(report.human(result, args.show_suppressed))
    return 1 if result.active() else 0


if __name__ == "__main__":
    sys.exit(main())
