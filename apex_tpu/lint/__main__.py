"""``python -m apex_tpu.lint`` / the ``apex-tpu-lint`` console script.

Exit status: 0 = clean (no unsuppressed, non-baselined findings),
1 = findings (including files that failed to parse), 2 = usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

from . import engine, report, rules


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="apex-tpu-lint",
        description="AST-based TPU-hazard analyzer (rule catalog: "
                    "docs/lint.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: apex_tpu "
                         "and examples under the cwd, else the cwd)")
    ap.add_argument("--format", choices=["human", "json"], default="human")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", default=None,
                    help="comma-separated rule ids to skip")
    ap.add_argument("--baseline", default=engine.DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings "
                         "(default: the checked-in package baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as live")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather every current finding into "
                         "--baseline and exit 0")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed/baselined findings")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in rules.rule_ids():
            r = rules.REGISTRY[rid]
            print(f"{rid}: {r.summary}")
        return 0

    paths = args.paths
    if not paths:
        paths = [p for p in ("apex_tpu", "examples") if os.path.isdir(p)]
        if not paths:
            paths = ["."]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"apex-tpu-lint: no such path(s): {missing}",
              file=sys.stderr)
        return 2

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    baseline = None if args.no_baseline else args.baseline
    try:
        result = engine.run(paths, select=select, ignore=ignore,
                            baseline=baseline)
    except KeyError as e:
        print(f"apex-tpu-lint: {e.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        n = engine.write_baseline(args.baseline, result,
                                  result._modules_by_rel)
        print(f"apex-tpu-lint: baselined {n} finding(s) -> "
              f"{args.baseline}")
        return 0

    if args.format == "json":
        print(report.as_json(result, args.show_suppressed))
    else:
        print(report.human(result, args.show_suppressed))
    return 1 if result.active() else 0


if __name__ == "__main__":
    sys.exit(main())
