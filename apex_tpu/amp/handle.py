"""scale_loss context manager and cast-disable scope
(reference: apex/amp/handle.py:17-167).

Same observable flow as the reference: enter → ``_prepare_amp_backward`` per
optimizer, yield ``loss.float() * loss_scale``; exit → clear overflow state,
``_post_amp_backward`` (unscale model grads into master grads),
``update_scale``; on overflow, one-shot patch ``optimizer.step`` to skip and
print the "Gradient overflow" message.
"""
from __future__ import annotations

import contextlib

from ._amp_state import _amp_state, maybe_print
from . import policy as _policy


@contextlib.contextmanager
def scale_loss(loss, optimizers, loss_id=0, model=None, delay_unscale=False,
               delay_overflow_check=False):
    if _amp_state.opt_properties is None:
        raise RuntimeError(
            "Invoked 'with amp.scale_loss', but internal Amp state has not "
            "been initialized.  model, optimizer = amp.initialize(model, "
            "optimizer, opt_level=...) must be called before "
            "'with amp.scale_loss'.")

    if not _amp_state.opt_properties.enabled:
        yield loss
        return

    from ..optimizers.base import Optimizer
    from ..parallel.LARC import LARC

    if isinstance(optimizers, (Optimizer, LARC)):
        optimizers = [optimizers]

    loss_scaler = _amp_state.loss_scalers[loss_id]
    loss_scale = loss_scaler.loss_scale()

    if ((not _amp_state.opt_properties.master_weights)
            and (not loss_scaler.dynamic)
            and loss_scale == 1.0):
        yield loss.float()
        return

    if not delay_unscale:
        if isinstance(optimizers, list):
            for optimizer in optimizers:
                if not optimizer._amp_stash.params_have_scaled_gradients:
                    optimizer._prepare_amp_backward()

    yield loss.float() * loss_scale

    from ..runtime import chaos as _chaos
    if _chaos.active() and _chaos.hook(
            "amp.backward", loss_id=loss_id) == "nonfinite_grads":
        # chaos: poison every produced gradient so the scaler's own
        # overflow machinery (flag → skip → halve) fires — the eager
        # surface's analogue of the fused step's batch taint
        for optimizer in optimizers:
            stash = getattr(optimizer, "_amp_stash", None)
            param_lists = [g["params"] for g in optimizer.param_groups]
            for name in ("all_fp16_params", "all_fp32_params",
                         "all_fp32_from_fp32_params"):
                lst = getattr(stash, name, None)
                if lst:
                    param_lists.append(lst)
            for params in param_lists:
                for p in params:
                    if getattr(p, "grad", None) is not None:
                        p.grad = p.grad * float("nan")

    if delay_unscale:
        for optimizer in optimizers:
            optimizer._amp_stash.params_have_scaled_gradients = True
            # remember WHICH scaler the scaled gradients carry, so a
            # ``step()`` issued without a final non-delayed scale_loss can
            # finalize the unscale itself (exactly once) instead of
            # stepping on scaled gradients — see
            # _process_optimizer.finalize_delayed_unscale
            optimizer._amp_stash._delayed_scaler = loss_scaler
    else:
        from ..observe import spans as _spans
        loss_scaler.clear_overflow_state()
        # the eager surface's unscale+overflow-check region — span'd so
        # device profiles separate it from the backward that produced the
        # scaled gradients
        with _spans.span("amp.backward", loss_id=loss_id):
            for optimizer in optimizers:
                optimizer._post_amp_backward(loss_scaler)
                optimizer._amp_stash.params_have_scaled_gradients = False
                optimizer._amp_stash._delayed_scaler = None
        # deferred mode (amp.initialize(..., defer_scale_update=True)): hand
        # the scaler to the optimizers' executor programs
        # (runtime.executor.optimizer_step_with_scaler), which fuse the
        # overflow-conditional skip (lax.cond) and the dynamic-scale update
        # into the step executable — no per-step host sync, no step patching
        # (and no "Gradient overflow" print; read loss_scale() to observe).
        # (single-optimizer only: the scale update runs inside that
        # optimizer's step program exactly once)
        if (not delay_overflow_check
                and len(optimizers) == 1
                and getattr(_amp_state.opt_properties, "defer_scale_update",
                            False)
                and getattr(optimizers[0], "_step_cache_scaler_ok", False)):
            optimizers[0]._amp_stash._deferred_scaler = loss_scaler
            return
        should_skip = False if delay_overflow_check else \
            loss_scaler.update_scale()
        if should_skip:
            for optimizer in optimizers:
                if not optimizer._amp_stash.already_patched:
                    def patch_step(opt, scaler, idx):
                        opt_step = opt.step

                        def skip_step(closure=None):
                            if closure is not None:
                                raise RuntimeError(
                                    "Currently, Amp does not support closure "
                                    "use with optimizers.")
                            maybe_print(
                                "Gradient overflow.  Skipping step, loss "
                                f"scaler {idx} reducing loss scale to "
                                f"{scaler.loss_scale()}")
                            if hasattr(opt._amp_stash,
                                       "all_fp32_from_fp16_params"):
                                for param in \
                                        opt._amp_stash.all_fp32_from_fp16_params:
                                    param.grad = None
                            if hasattr(opt, "most_recent_scale"):
                                opt.most_recent_scale = 1.0
                                opt.scale_set_by_backward = False
                            opt.step = opt_step
                            opt._amp_stash.already_patched = False
                            # resilience.BadStepGuard (attach_optimizer):
                            # a skip on this reference-exact path never
                            # reaches the guard's step wrapper (THIS
                            # function replaced it for the skipped call),
                            # so notify it here — the skip decision is
                            # host-known, no device flag involved
                            guard = getattr(opt._amp_stash, "_guard", None)
                            if guard is not None:
                                guard.observe(1)

                        return skip_step

                    optimizer.step = patch_step(optimizer, loss_scaler,
                                                loss_id)
                    optimizer._amp_stash.already_patched = True


# Free-function cast-disable scope (reference handle.py:163-167).
disable_casts = _policy.disable_casts


class AmpHandle:
    """Legacy old-API handle (reference handle.py:170-252), returned by
    :func:`init`.  Activation = installing an ambient O1 CastPolicy (the
    trace-time analogue of the reference's global torch patching); the
    cast-cache plumbing (``has_cache``/``cache``/``remove_cache``) is kept
    for API parity but is inert — there is no weight-cast cache to
    invalidate at trace time.
    """

    def __init__(self, loss_scale="dynamic", enable_caching=True,
                 verbose=False, allow_banned=False):
        from .frontend import get_default_half_dtype
        from .scaler import LossScaler
        self._enable_caching = enable_caching
        self._verbose = verbose
        self._cache = {}
        self._loss_scale = loss_scale
        self._default_scaler = LossScaler(loss_scale)
        self._is_active = True
        self._policy = _policy.CastPolicy(
            half_dtype=get_default_half_dtype(), enabled=True,
            allow_banned=allow_banned, verbose=verbose)
        _policy.replay_registrations(self._policy)
        _amp_state.handle = self._policy
        _amp_state.ambient_policy = self._policy

    def is_active(self):
        return self._is_active and _amp_state.ambient_policy is self._policy

    @contextlib.contextmanager
    def _disable_casts(self):
        self._is_active = False
        try:
            with _policy.disable_casts():
                yield
        finally:
            self._is_active = True

    def wrap_optimizer(self, optimizer, num_loss=1):
        from .opt import OptimWrapper
        self._default_scaler = None
        return OptimWrapper(optimizer, self, num_loss,
                            loss_scale=self._loss_scale)

    def scale_loss(self, loss, optimizer):
        raise RuntimeError(
            "The old Amp API's handle.scale_loss is no longer supported.  "
            "Use handle.wrap_optimizer(optimizer).scale_loss(loss), or move "
            "to the amp.initialize API.")

    def _clear_cache(self):
        self._cache.clear()

    def _deactivate(self):
        """Uninstall the ambient policy (reference handle.py:233-236
        restores the patched torch functions)."""
        if _amp_state.ambient_policy is self._policy:
            _amp_state.ambient_policy = None
            _amp_state.handle = None

    @property
    def has_cache(self):
        return self._enable_caching

    @property
    def cache(self):
        return self._cache

    def remove_cache(self, param):
        if self.has_cache and param in self.cache:
            del self.cache[param]

    @property
    def verbose(self):
        return self._verbose


class NoOpHandle:
    """Returned by ``init(enabled=False)`` (reference handle.py:254-281)."""

    def is_active(self):
        return False

    @contextlib.contextmanager
    def _disable_casts(self):
        yield

    def wrap_optimizer(self, optimizer, num_loss=1):
        from .opt import OptimWrapper
        return OptimWrapper(optimizer, self, num_loss)

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer):
        yield loss

    @property
    def has_cache(self):
        return False

    @property
    def verbose(self):
        return False

    def _clear_cache(self):
        pass

    def _deactivate(self):
        pass


def init(enabled=True, loss_scale="dynamic", enable_caching=True,
         verbose=False, allow_banned=False):
    """Legacy old-API entry point (reference amp.py:68-177): returns a
    handle whose construction activates autocasting globally.  The modern
    path is ``amp.initialize``; this exists for scripts written against the
    pre-initialize API (``handle.wrap_optimizer`` + per-loss scalers).
    """
    if not enabled:
        return NoOpHandle()
    return AmpHandle(loss_scale, enable_caching, verbose, allow_banned)
