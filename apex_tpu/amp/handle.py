"""scale_loss context manager and cast-disable scope
(reference: apex/amp/handle.py:17-167).

Same observable flow as the reference: enter → ``_prepare_amp_backward`` per
optimizer, yield ``loss.float() * loss_scale``; exit → clear overflow state,
``_post_amp_backward`` (unscale model grads into master grads),
``update_scale``; on overflow, one-shot patch ``optimizer.step`` to skip and
print the "Gradient overflow" message.
"""
from __future__ import annotations

import contextlib

from ._amp_state import _amp_state, maybe_print
from . import policy as _policy


@contextlib.contextmanager
def scale_loss(loss, optimizers, loss_id=0, model=None, delay_unscale=False,
               delay_overflow_check=False):
    if _amp_state.opt_properties is None:
        raise RuntimeError(
            "Invoked 'with amp.scale_loss', but internal Amp state has not "
            "been initialized.  model, optimizer = amp.initialize(model, "
            "optimizer, opt_level=...) must be called before "
            "'with amp.scale_loss'.")

    if not _amp_state.opt_properties.enabled:
        yield loss
        return

    from ..optimizers.base import Optimizer
    from ..parallel.LARC import LARC

    if isinstance(optimizers, (Optimizer, LARC)):
        optimizers = [optimizers]

    loss_scaler = _amp_state.loss_scalers[loss_id]
    loss_scale = loss_scaler.loss_scale()

    if ((not _amp_state.opt_properties.master_weights)
            and (not loss_scaler.dynamic)
            and loss_scale == 1.0):
        yield loss.float()
        return

    if not delay_unscale:
        if isinstance(optimizers, list):
            for optimizer in optimizers:
                if not optimizer._amp_stash.params_have_scaled_gradients:
                    optimizer._prepare_amp_backward()

    yield loss.float() * loss_scale

    if delay_unscale:
        for optimizer in optimizers:
            optimizer._amp_stash.params_have_scaled_gradients = True
    else:
        loss_scaler.clear_overflow_state()
        for optimizer in optimizers:
            optimizer._post_amp_backward(loss_scaler)
            optimizer._amp_stash.params_have_scaled_gradients = False
        should_skip = False if delay_overflow_check else \
            loss_scaler.update_scale()
        if should_skip:
            for optimizer in optimizers:
                if not optimizer._amp_stash.already_patched:
                    def patch_step(opt, scaler, idx):
                        opt_step = opt.step

                        def skip_step(closure=None):
                            if closure is not None:
                                raise RuntimeError(
                                    "Currently, Amp does not support closure "
                                    "use with optimizers.")
                            maybe_print(
                                "Gradient overflow.  Skipping step, loss "
                                f"scaler {idx} reducing loss scale to "
                                f"{scaler.loss_scale()}")
                            if hasattr(opt._amp_stash,
                                       "all_fp32_from_fp16_params"):
                                for param in \
                                        opt._amp_stash.all_fp32_from_fp16_params:
                                    param.grad = None
                            if hasattr(opt, "most_recent_scale"):
                                opt.most_recent_scale = 1.0
                                opt.scale_set_by_backward = False
                            opt.step = opt_step
                            opt._amp_stash.already_patched = False

                        return skip_step

                    optimizer.step = patch_step(optimizer, loss_scaler,
                                                loss_id)
                    optimizer._amp_stash.already_patched = True


# Free-function cast-disable scope (reference handle.py:163-167).
disable_casts = _policy.disable_casts
