"""Shared amp session state (reference: apex/amp/_amp_state.py).

A module-level stash through which frontend / handle / initialize communicate.
"""
from __future__ import annotations

import jax


class AmpState:
    def __init__(self):
        self.hard_override = False
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        # populated by amp.initialize:
        self.opt_properties = None
        self.loss_scalers = []
        self.handle = None
        # O1's session policy, applied ambiently to every Module call
        # (the analogue of the reference patching torch globally)
        self.ambient_policy = None


_amp_state = AmpState()


def reset():
    """Clear the initialize-populated session state so a fresh
    ``amp.initialize`` can run in the same process (tests, notebooks)."""
    _amp_state.opt_properties = None
    _amp_state.loss_scalers = []
    _amp_state.handle = None
    _amp_state.ambient_policy = None


def warn_or_err(msg):
    if _amp_state.hard_override:
        print("Warning:  " + msg)
    else:
        raise RuntimeError(msg)


def maybe_print(msg, rank0=False):
    """Verbosity-gated print; rank0 gating through the sanctioned
    topology helpers (the reference gates on torch.distributed rank,
    _amp_state.py:38-50)."""
    if _amp_state.verbosity > 0:
        if rank0:
            from ..parallel.distributed import num_processes, rank
            if num_processes() > 1 and rank() != 0:
                return
        print(msg)


def master_params(optimizer):
    """Iterate the (master) params owned by ``optimizer``
    (reference: _amp_state.py:59-68).  Used e.g. for gradient clipping:
    ``clip_grad_norm(amp.master_params(optimizer), max_norm)``.
    """
    for group in optimizer.param_groups:
        for p in group["params"]:
            yield p
