"""In-place patching of optimizer instances for amp
(reference: apex/amp/_process_optimizer.py).

Same observable machinery as the reference: an ``_amp_stash`` holding
fp16/master param groups, lazy master-weight creation (half param → fp32
master swapped into ``param_groups``), patched ``step`` (master→model copyback),
``zero_grad``, ``add_param_group``, and the ``_prepare_amp_backward`` /
``_post_amp_backward`` pair the ``scale_loss`` context drives.  "fp16" here
means the session's half dtype (float16 or bfloat16).
"""
from __future__ import annotations

import types

import jax
import jax.numpy as jnp

from ..nn.parameter import Parameter
from ..runtime import executor as _executor
from ._amp_state import maybe_print


def _is_half(p):
    return jnp.dtype(p.dtype) in (jnp.dtype(jnp.float16),
                                  jnp.dtype(jnp.bfloat16))


def _is_fp32(p):
    return jnp.dtype(p.dtype) == jnp.dtype(jnp.float32)


class AmpOptimizerState:
    pass


def _master_params_to_model_params(self):
    stash = self._amp_stash
    if len(stash.all_fp16_params) > 0:
        # one cached executable; the stale half copies are donated (each
        # output aliases the buffer it replaces)
        new_model = _executor.master_to_model(
            [p.data for p in stash.all_fp32_from_fp16_params],
            [p.data for p in stash.all_fp16_params])
        for mp, nd in zip(stash.all_fp16_params, new_model):
            mp.data = nd


def lazy_init_with_master_weights(self):
    stash = self._amp_stash
    stash.fp16_groups = []
    stash.fp32_from_fp16_groups = []
    stash.fp32_from_fp32_groups = []
    for i, param_group in enumerate(self.param_groups):
        fp16_params_this_group = []
        fp32_params_this_group = []
        fp32_from_fp16_params_this_group = []
        for i, param in enumerate(param_group["params"]):
            if param.requires_grad:
                if _is_half(param):
                    fp16_params_this_group.append(param)
                    master = Parameter(param.data.astype(jnp.float32),
                                       name=param.name)
                    param_group["params"][i] = master
                    fp32_from_fp16_params_this_group.append(master)
                    if param in self.state:
                        self.state[master] = self.state.pop(param)
                elif _is_fp32(param):
                    fp32_params_this_group.append(param)
                else:
                    raise TypeError(
                        "Optimizer's parameters must be float32 or half "
                        f"(float16/bfloat16). Received {param.dtype}")
        stash.fp16_groups.append(fp16_params_this_group)
        stash.fp32_from_fp16_groups.append(fp32_from_fp16_params_this_group)
        stash.fp32_from_fp32_groups.append(fp32_params_this_group)

    stash.all_fp16_params = [p for g in stash.fp16_groups for p in g]
    stash.all_fp32_from_fp16_params = [
        p for g in stash.fp32_from_fp16_groups for p in g]
    stash.all_fp32_from_fp32_params = [
        p for g in stash.fp32_from_fp32_groups for p in g]

    stash.all_fp16_grad_stash = [None] * len(stash.all_fp16_params)
    stash.all_fp32_from_fp32_grad_stash = \
        [None] * len(stash.all_fp32_from_fp32_params)

    for param in stash.all_fp32_from_fp16_params:
        param.grad = None
    for param in stash.all_fp32_from_fp32_params:
        param.grad = None


def post_backward_models_are_masters(scaler, params, stashed_grads,
                                     scale_override=None):
    # device scalar, NOT loss_scale() — the reference pays one D2H sync per
    # step here (scaler.py:197-200); the step-cache path keeps the scale on
    # device end to end
    grads_have_scale = scaler.device_scale
    stashed_have_scale, out_scale = 1.0, 1.0

    if not scaler.dynamic and scaler.static_scale == 1.0:
        for i in range(len(stashed_grads)):
            stashed_grads[i] = None
        return

    if scale_override is not None:
        grads_have_scale, stashed_have_scale, out_scale = scale_override

    grads_needing_unscale = []
    grads_needing_unscale_with_stash = []
    stashed = []
    for param, stashed_grad in zip(params, stashed_grads):
        if param.grad is None and stashed_grad is not None:
            param.grad = stashed_grad
        elif param.grad is not None and stashed_grad is None:
            grads_needing_unscale.append(param)
        elif param.grad is not None and stashed_grad is not None:
            grads_needing_unscale_with_stash.append(param)
            stashed.append(stashed_grad)

    if grads_needing_unscale:
        new = scaler.unscale(
            [p.grad for p in grads_needing_unscale],
            [p.grad for p in grads_needing_unscale],
            None, models_are_masters=True,
            scale_override=grads_have_scale / out_scale)
        for p, g in zip(grads_needing_unscale, new):
            p.grad = g

    if grads_needing_unscale_with_stash:
        new = scaler.unscale_with_stashed(
            [p.grad for p in grads_needing_unscale_with_stash],
            stashed,
            [p.grad for p in grads_needing_unscale_with_stash],
            scale_override=(grads_have_scale, stashed_have_scale, out_scale))
        for p, g in zip(grads_needing_unscale_with_stash, new):
            p.grad = g

    for i in range(len(stashed_grads)):
        stashed_grads[i] = None


def prepare_backward_with_master_weights(self):
    stash = self._amp_stash
    self._amp_lazy_init()
    for param in stash.all_fp16_params:
        # grad copy elision (reference _process_optimizer.py:145-149)
        param.grad = None
    for i, param in enumerate(stash.all_fp32_from_fp32_params):
        stash.all_fp32_from_fp32_grad_stash[i] = param.grad
        param.grad = None


def post_backward_with_master_weights(self, scaler):
    stash = self._amp_stash
    self._amp_lazy_init()

    fp16_needing_unscale = []
    new_masters = []
    fp16_needing_unscale_with_stash = []
    preexisting_masters = []
    for fp16_param, fp32_param in zip(stash.all_fp16_params,
                                      stash.all_fp32_from_fp16_params):
        if fp16_param.grad is None:
            continue
        if fp32_param.grad is None:
            fp16_needing_unscale.append(fp16_param)
            new_masters.append(fp32_param)
        else:
            fp16_needing_unscale_with_stash.append(fp16_param)
            preexisting_masters.append(fp32_param)

    if fp16_needing_unscale:
        # master templates only supply dtypes — ShapeDtypeStructs avoid a
        # per-step fp32 allocation per gradient; device_scale avoids the
        # per-step host sync of loss_scale()
        new = scaler.unscale(
            [p.grad for p in fp16_needing_unscale],
            [jax.ShapeDtypeStruct(tuple(p.shape), jnp.float32)
             for p in fp16_needing_unscale],
            scaler.device_scale, models_are_masters=False)
        for mp, g in zip(new_masters, new):
            mp.grad = g

    if fp16_needing_unscale_with_stash:
        new = scaler.unscale_with_stashed(
            [p.grad for p in fp16_needing_unscale_with_stash],
            [p.grad for p in preexisting_masters],
            [p.grad for p in preexisting_masters])
        for mp, g in zip(preexisting_masters, new):
            mp.grad = g

    post_backward_models_are_masters(
        scaler, stash.all_fp32_from_fp32_params,
        stash.all_fp32_from_fp32_grad_stash)


def lazy_init_no_master_weights(self):
    stash = self._amp_stash
    stash.all_fp16_params = []
    stash.all_fp32_params = []
    for param_group in self.param_groups:
        for param in param_group["params"]:
            if _is_half(param):
                stash.all_fp16_params.append(param)
            elif _is_fp32(param):
                stash.all_fp32_params.append(param)
            else:
                raise TypeError(
                    "Optimizer's parameters must be float32 or half "
                    f"(float16/bfloat16). Received {param.dtype}")
    stash.all_fp16_grad_stash = [None] * len(stash.all_fp16_params)
    stash.all_fp32_grad_stash = [None] * len(stash.all_fp32_params)


def prepare_backward_no_master_weights(self):
    stash = self._amp_stash
    self._amp_lazy_init()
    for i, param in enumerate(stash.all_fp16_params):
        stash.all_fp16_grad_stash[i] = param.grad
        param.grad = None
    for i, param in enumerate(stash.all_fp32_params):
        stash.all_fp32_grad_stash[i] = param.grad
        param.grad = None


def post_backward_no_master_weights(self, scaler):
    stash = self._amp_stash
    self._amp_lazy_init()
    split_types = ((stash.all_fp16_params, stash.all_fp16_grad_stash),
                   (stash.all_fp32_params, stash.all_fp32_grad_stash))
    for params, stashed_grads in split_types:
        post_backward_models_are_masters(scaler, params, stashed_grads)


# --------------------------------------------------------------------------
# FusedSGD versions (reference _process_optimizer.py:252-310): FusedSGD can
# keep scaled grads and fold 1/scale into the kernel itself.
# --------------------------------------------------------------------------

def prepare_backward_with_master_weights_FusedSGD(self):
    if self.materialize_master_grads:
        prepare_backward_with_master_weights(self)
    else:
        stash = self._amp_stash
        self._amp_lazy_init()
        for i, param in enumerate(stash.all_fp16_params):
            stash.all_fp16_grad_stash[i] = param.grad
            param.grad = None
        for i, param in enumerate(stash.all_fp32_from_fp32_params):
            stash.all_fp32_from_fp32_grad_stash[i] = param.grad
            param.grad = None


def post_backward_with_master_weights_FusedSGD(self, scaler):
    if self.materialize_master_grads:
        post_backward_with_master_weights(self, scaler)
    else:
        stash = self._amp_stash
        self._amp_lazy_init()

        grads_have_scale = scaler.loss_scale()
        stashed_have_scale = self.most_recent_scale
        out_scale = grads_have_scale
        if self.scale_set_by_backward:
            out_scale = min(grads_have_scale, self.most_recent_scale)

        split_types = (
            (stash.all_fp16_params, stash.all_fp16_grad_stash),
            (stash.all_fp32_from_fp32_params,
             stash.all_fp32_from_fp32_grad_stash))
        for params, stashed_grads in split_types:
            post_backward_models_are_masters(
                scaler, params, stashed_grads,
                (grads_have_scale, stashed_have_scale, out_scale))

        self.most_recent_scale = out_scale
        self.scale_set_by_backward = True


def prepare_backward_no_master_weights_FusedSGD(self):
    prepare_backward_no_master_weights(self)


def post_backward_no_master_weights_FusedSGD(self, scaler):
    post_backward_no_master_weights(self, scaler)


def finalize_delayed_unscale(optimizer, scaler=None):
    """Settle gradients left scaled by ``scale_loss(delay_unscale=True)``.

    The delayed-accumulation contract is that the LAST backward of the
    window runs under a non-delayed ``scale_loss``, whose exit unscales
    the accumulated gradients.  When the caller instead goes straight to
    ``optimizer.step()`` the gradients are still scaled — stepping on
    them would apply a loss_scale-times-too-large update, and a later
    non-delayed ``scale_loss`` over the same buffers would unscale a
    SECOND time.  The step wrappers call this first: it performs the one
    pending unscale + dynamic-scale update and clears the stash flag, so
    the window's unscale runs exactly once no matter how the caller ends
    the window.  Returns ``(finalized, should_skip, scaler)``.
    """
    stash = optimizer._amp_stash
    if not getattr(stash, "params_have_scaled_gradients", False):
        return False, False, None
    if scaler is None:
        scaler = getattr(stash, "_delayed_scaler", None)
    if scaler is None:
        from ._amp_state import _amp_state
        scaler = _amp_state.loss_scalers[0]
    scaler.clear_overflow_state()
    optimizer._post_amp_backward(scaler)
    stash.params_have_scaled_gradients = False
    stash._delayed_scaler = None
    return True, scaler.update_scale(), scaler


def _skip_delayed_overflow_step(optimizer, scaler):
    """The overflow-skip behavior of handle.patch_step, for a window whose
    unscale was finalized at step() time instead of at scale_loss exit."""
    stash = optimizer._amp_stash
    maybe_print(
        "Gradient overflow.  Skipping step, loss scaler reducing loss "
        f"scale to {scaler.loss_scale()}")
    if hasattr(stash, "all_fp32_from_fp16_params"):
        for param in stash.all_fp32_from_fp16_params:
            param.grad = None
    if hasattr(optimizer, "most_recent_scale"):
        optimizer.most_recent_scale = 1.0
        optimizer.scale_set_by_backward = False
    guard = getattr(stash, "_guard", None)
    if guard is not None:
        guard.observe(1)


def _amp_lazy_init(self):
    stash = self._amp_stash
    if not stash.lazy_init_called:
        self._lazy_init_maybe_master_weights()
        stash.lazy_init_called = True


def _process_optimizer(optimizer, properties):
    from ..optimizers import FusedSGD

    if hasattr(optimizer, "_amp_stash"):
        raise RuntimeError("A given optimizer should only be passed through "
                           "amp.initialize once.")
    optimizer._amp_stash = AmpOptimizerState()
    optimizer._amp_stash.lazy_init_called = False
    optimizer._amp_stash.already_patched = False
    optimizer._amp_stash.params_have_scaled_gradients = False
    # step-cache integration: set when the fused step program emitted the
    # master→model half copies itself / when scale_loss deferred the
    # dynamic-scale update into the step program
    optimizer._amp_stash._model_params_synced = False
    optimizer._amp_stash._deferred_scaler = None
    # scaler whose scaled gradients are pending from scale_loss
    # (delay_unscale=True) — consumed by finalize_delayed_unscale
    optimizer._amp_stash._delayed_scaler = None

    for name in ("_lazy_init_maybe_master_weights",
                 "_master_params_to_model_params",
                 "_prepare_amp_backward",
                 "_post_amp_backward",
                 "_amp_lazy_init"):
        if hasattr(optimizer, name):
            raise RuntimeError(
                f"Incoming optimizer already has {name} defined.")

    if properties.master_weights:
        optimizer._lazy_init_maybe_master_weights = types.MethodType(
            lazy_init_with_master_weights, optimizer)
        optimizer._master_params_to_model_params = types.MethodType(
            _master_params_to_model_params, optimizer)

        old_step = optimizer.step

        def new_step(self, closure=None):
            if closure is not None:
                raise RuntimeError("Currently, Amp does not support closure "
                                   "use with optimizers.")
            _, should_skip, scaler = finalize_delayed_unscale(self)
            if should_skip:
                _skip_delayed_overflow_step(self, scaler)
                return None
            retval = old_step()
            if not isinstance(self, FusedSGD):
                stash = self._amp_stash
                if getattr(stash, "_model_params_synced", False):
                    # the step-cache program emitted the half model copies
                    # from the same executable as the update — no separate
                    # copyback pass
                    stash._model_params_synced = False
                else:
                    self._master_params_to_model_params()
            for param in self._amp_stash.all_fp32_from_fp16_params:
                param.grad = None
            return retval

        optimizer.step = types.MethodType(new_step, optimizer)

        old_zero_grad = optimizer.zero_grad  # noqa: F841 (kept for parity)

        def new_zero_grad(self, set_to_none: bool = None):
            if set_to_none is None:
                # fused-path default: the step cache consumes gradients
                # functionally, so dropping them skips the per-param
                # zeros_like allocation entirely
                set_to_none = getattr(self, "set_grad_none", True)
            stash = self._amp_stash
            self._amp_lazy_init()
            for param in stash.all_fp16_params:
                if param.grad is not None:
                    param.grad = None if set_to_none \
                        else jnp.zeros_like(param.grad)
            for param in stash.all_fp32_from_fp32_params:
                if param.grad is not None:
                    param.grad = None if set_to_none \
                        else jnp.zeros_like(param.grad)
            for param in self._amp_stash.all_fp32_from_fp16_params:
                param.grad = None

        optimizer.zero_grad = types.MethodType(new_zero_grad, optimizer)

        if isinstance(optimizer, FusedSGD):
            optimizer._prepare_amp_backward = types.MethodType(
                prepare_backward_with_master_weights_FusedSGD, optimizer)
            optimizer._post_amp_backward = types.MethodType(
                post_backward_with_master_weights_FusedSGD, optimizer)
        else:
            optimizer._prepare_amp_backward = types.MethodType(
                prepare_backward_with_master_weights, optimizer)
            optimizer._post_amp_backward = types.MethodType(
                post_backward_with_master_weights, optimizer)
    else:
        optimizer._lazy_init_maybe_master_weights = types.MethodType(
            lazy_init_no_master_weights, optimizer)

        old_step_nm = optimizer.step

        def new_step_nm(self, closure=None):
            # delayed-unscale guard, as on the master-weights path: a
            # step() closing an all-delayed accumulation window finalizes
            # the pending unscale exactly once
            _, should_skip, scaler = finalize_delayed_unscale(self)
            if should_skip:
                _skip_delayed_overflow_step(self, scaler)
                return None
            return old_step_nm() if closure is None else old_step_nm(closure)

        optimizer.step = types.MethodType(new_step_nm, optimizer)

        if isinstance(optimizer, FusedSGD):
            optimizer._prepare_amp_backward = types.MethodType(
                prepare_backward_no_master_weights_FusedSGD, optimizer)
            optimizer._post_amp_backward = types.MethodType(
                post_backward_no_master_weights_FusedSGD, optimizer)
        else:
            optimizer._prepare_amp_backward = types.MethodType(
                prepare_backward_no_master_weights, optimizer)
            optimizer._post_amp_backward = types.MethodType(
                post_backward_no_master_weights, optimizer)

    optimizer._amp_lazy_init = types.MethodType(_amp_lazy_init, optimizer)

    old_add_param_group = optimizer.add_param_group

    def new_add_param_group(self, new_group):
        stash = self._amp_stash
        if not stash.lazy_init_called:
            self._lazy_init_maybe_master_weights()
            stash.lazy_init_called = True

        assert isinstance(new_group, dict), "param group must be a dict"
        new_params = new_group["params"]
        if isinstance(new_params, Parameter):
            new_group["params"] = [new_params]
        elif isinstance(new_params, set):
            raise TypeError("optimizer parameters need to be organized in "
                            "ordered collections; sets are not allowed.")
        else:
            new_group["params"] = list(new_params)

        if properties.master_weights:
            fp16_params_this_group = []
            fp32_params_this_group = []
            fp32_from_fp16_params_this_group = []
            for i, param in enumerate(new_group["params"]):
                if param.requires_grad:
                    if _is_half(param):
                        fp16_params_this_group.append(param)
                        master = Parameter(param.data.astype(jnp.float32),
                                           name=param.name)
                        new_group["params"][i] = master
                        fp32_from_fp16_params_this_group.append(master)
                    elif _is_fp32(param):
                        fp32_params_this_group.append(param)
                    else:
                        raise TypeError(
                            "Optimizer's parameters must be float32 or half "
                            f"(float16/bfloat16). Received {param.dtype}")
            stash.fp16_groups.append(fp16_params_this_group)
            stash.fp32_from_fp16_groups.append(
                fp32_from_fp16_params_this_group)
            stash.fp32_from_fp32_groups.append(fp32_params_this_group)
            stash.all_fp16_params += fp16_params_this_group
            stash.all_fp32_from_fp16_params += \
                fp32_from_fp16_params_this_group
            stash.all_fp32_from_fp32_params += fp32_params_this_group
            stash.all_fp16_grad_stash += [None] * len(fp16_params_this_group)
            stash.all_fp32_from_fp32_grad_stash += \
                [None] * len(fp32_params_this_group)
        else:
            for param in new_group["params"]:
                if _is_half(param):
                    stash.all_fp16_params.append(param)
                    stash.all_fp16_grad_stash.append(None)
                elif _is_fp32(param):
                    stash.all_fp32_params.append(param)
                    stash.all_fp32_grad_stash.append(None)
                else:
                    raise TypeError(
                        "Optimizer's parameters must be float32 or half "
                        f"(float16/bfloat16). Received {param.dtype}")

        old_add_param_group(new_group)

    optimizer.add_param_group = types.MethodType(new_add_param_group,
                                                 optimizer)
    return optimizer
