"""amp frontend: opt-level presets, ``initialize``, amp checkpoint state.

Reference: apex/amp/frontend.py.  O0–O3 are property bundles; ``initialize``
validates kwarg overrides against the chosen preset and delegates to
``_initialize``.  TPU adaptations:

* dtypes are jnp dtypes; ``"float16"``/``"bfloat16"``/``"float32"`` strings and
  torch dtypes are accepted and resolved.  The presets default to float16 for
  reference parity; on TPU, bf16 is usually the right choice — pass
  ``cast_model_type="bfloat16"`` (O2/O3) or call
  ``amp.set_default_half_dtype("bfloat16")`` before ``initialize``.  With bf16
  a ``loss_scale=1.0`` static scaler is typically sufficient; dynamic scaling
  still works and is exercised for parity testing (SURVEY.md §7 hard parts).
* ``patch_torch_functions`` keeps its name (it now toggles the trace-time cast
  policy rather than monkey-patching torch).
* the patched ``optimizer.step()`` / ``scale_loss`` machinery compiles its
  unscale + update programs through ``runtime.executor`` (one dispatch choke
  point shared with the fused step — see docs/executor.md); ``initialize``
  itself only configures the cast/scaling properties.
"""
from __future__ import annotations

from collections import OrderedDict

import jax.numpy as jnp

from ._amp_state import _amp_state, maybe_print, warn_or_err

_DTYPE_ALIASES = {
    "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "float32": jnp.float32, "fp32": jnp.float32, "float": jnp.float32,
}

_default_half_dtype = jnp.float16


def set_default_half_dtype(dtype):
    """Set what 'half' means for the O1-O3 presets (float16 or bfloat16)."""
    global _default_half_dtype
    _default_half_dtype = resolve_dtype(dtype)


def get_default_half_dtype():
    return _default_half_dtype


def resolve_dtype(value):
    """Resolve strings / numpy / jnp / torch dtypes to a jnp dtype."""
    if value is None:
        return None
    if isinstance(value, str):
        try:
            return _DTYPE_ALIASES[value.lower()]
        except KeyError:
            raise ValueError(f"Unknown dtype string {value!r}") from None
    mod = type(value).__module__
    if mod.startswith("torch"):  # torch.dtype, without importing torch
        name = str(value).split(".")[-1]
        return _DTYPE_ALIASES[name]
    return jnp.dtype(value).type


class Properties:
    """Default properties + consistency-checked attribute routing
    (reference: frontend.py:7-97)."""

    def __init__(self):
        self.options = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,
            "patch_torch_functions": False,
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
            # step-cache integration: fuse the overflow skip + dynamic-scale
            # update into the optimizer's compiled step (no per-step host
            # sync).  Off by default for reference-exact skip semantics
            # (one-shot step patch + "Gradient overflow" print).
            "defer_scale_update": False,
        }

    def _update_options_dict(self, new_options):
        for k, v in new_options:
            if k in self.options:
                self.options[k] = v
            else:
                raise ValueError(f"Tried to set unexpected option {k}")

    def __getattr__(self, name):
        if "options" in self.__dict__:
            options = self.__dict__["options"]
            if name in options:
                return options[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __setattr__(self, name, value):
        if "options" not in self.__dict__:
            super().__setattr__(name, value)
            return
        if name not in self.options:
            super().__setattr__(name, value)
            return
        if name == "cast_model_type":
            value = resolve_dtype(value) if not isinstance(value, bool) else value
            if self.opt_level == "O1" and value is not None:
                if value is not False and value is not jnp.float32:
                    warn_or_err(
                        "O1 inserts casts around functions rather than model "
                        "weights, so with O1, the model weights themselves "
                        "should remain FP32. If you wish to cast the model to "
                        "a different type, use opt_level='O2' or 'O3'. "
                        f"cast_model_type was {value}")
            self.options[name] = value
        elif name == "patch_torch_functions":
            if self.opt_level != "O1" and value:
                warn_or_err("Currently, patch_torch_functions=True should "
                            "only be set by selecting opt_level='O1'.")
            self.options[name] = value
        elif name == "keep_batchnorm_fp32":
            if self.opt_level == "O1" and value is not None:
                warn_or_err(
                    "With opt_level O1, batchnorm functions are automatically "
                    "patched to run in FP32, so keep_batchnorm_fp32 should be "
                    f"None. keep_batchnorm_fp32 was {value}")
            if value == "False":
                self.options[name] = False
            elif value == "True":
                self.options[name] = True
            else:
                assert value in (True, False, None), (
                    "keep_batchnorm_fp32 must be a boolean, the string 'True' "
                    f"or 'False', or None, found keep_batchnorm_fp32={value}")
                self.options[name] = value
        elif name == "master_weights":
            if self.opt_level == "O1" and value is not None:
                warn_or_err("It doesn't make sense to use master_weights with "
                            "O1. With O1, your model weights themselves should "
                            "be FP32.")
            self.options[name] = value
        elif name == "loss_scale":
            if value == "dynamic":
                self.options[name] = value
            else:
                self.options[name] = float(value)
        else:
            self.options[name] = value


class O3:
    brief = "O3:  Pure half-precision training."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = _default_half_dtype
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O2:
    brief = ("O2:  Half-precision training with FP32 batchnorm and FP32 "
             "master weights.")

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = _default_half_dtype
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        return properties


class O1:
    brief = "O1:  Insert automatic casts around compute functions."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.patch_torch_functions = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O0:
    brief = "O0:  Pure FP32 training."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


opt_levels = {"O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}


def initialize(models, optimizers=None, enabled=True, opt_level="O1",
               cast_model_type=None, patch_torch_functions=None,
               keep_batchnorm_fp32=None, master_weights=None, loss_scale=None,
               cast_model_outputs=None, num_losses=1, verbosity=1,
               min_loss_scale=None, max_loss_scale=2.0 ** 24,
               defer_scale_update=None):
    """Initialize models and optimizers for mixed-precision training
    (reference: frontend.py:195-358; same argument surface)."""
    from ._initialize import _initialize

    _amp_state.opt_properties = Properties()
    _amp_state.verbosity = verbosity
    _amp_state.ambient_policy = None

    if not enabled:
        handle = None
        _amp_state.handle = handle
        if optimizers is None:
            return models
        return models, optimizers

    if opt_level not in opt_levels:
        raise RuntimeError(
            f"Unexpected optimization level {opt_level}. Options are 'O0', "
            "'O1', 'O2', 'O3'.  Note that in `O0`, `O1`, etc., the prefix O "
            "is the letter O, not the number zero.")

    _amp_state.opt_properties = opt_levels[opt_level](_amp_state.opt_properties)
    maybe_print(f"Selected optimization level {opt_levels[opt_level].brief}",
                True)
    maybe_print("Defaults for this optimization level are:", True)
    for k, v in _amp_state.opt_properties.options.items():
        maybe_print(f"{k:22} : {v}", True)

    _amp_state.min_loss_scale = min_loss_scale
    _amp_state.max_loss_scale = max_loss_scale

    maybe_print("Processing user overrides (additional kwargs that are not "
                "None)...", True)
    for name, value in (("enabled", enabled),
                        ("cast_model_type", cast_model_type),
                        ("patch_torch_functions", patch_torch_functions),
                        ("keep_batchnorm_fp32", keep_batchnorm_fp32),
                        ("master_weights", master_weights),
                        ("loss_scale", loss_scale),
                        ("defer_scale_update", defer_scale_update)):
        if value is not None:
            setattr(_amp_state.opt_properties, name, value)

    maybe_print("After processing overrides, optimization options are:", True)
    for k, v in _amp_state.opt_properties.options.items():
        maybe_print(f"{k:22} : {v}", True)

    return _initialize(models, optimizers, _amp_state.opt_properties,
                       num_losses, cast_model_outputs)


def state_dict(destination=None):
    """amp checkpoint state: per-loss-scaler scale + unskipped counter
    (reference: frontend.py:361-370)."""
    if destination is None:
        destination = OrderedDict()
    for idx, loss_scaler in enumerate(_amp_state.loss_scalers):
        destination[f"loss_scaler{idx}"] = {
            "loss_scale": loss_scaler.loss_scale(),
            "unskipped": loss_scaler._unskipped,
        }
    return destination


def load_state_dict(state_dict):
    """Reference: frontend.py:373-400 (same warnings/errors)."""
    if len(state_dict) != len(_amp_state.loss_scalers):
        print(f"Warning: state_dict contains {len(state_dict)} entries, while "
              f"{len(_amp_state.loss_scalers)} loss_scalers are used")

    state_dict = state_dict.copy()
    nb_loss_scalers = len(_amp_state.loss_scalers)
    unexpected_keys = []
    idx = 0
    for key in state_dict:
        if "loss_scaler" not in key:
            unexpected_keys.append(key)
        else:
            if idx > (nb_loss_scalers - 1):
                print(f"Skipping loss_scaler[{idx}], since num_losses was set "
                      f"to {nb_loss_scalers}")
                break
            _amp_state.loss_scalers[idx]._loss_scale = \
                state_dict[key]["loss_scale"]
            _amp_state.loss_scalers[idx]._unskipped = \
                state_dict[key]["unskipped"]
            idx += 1

    if unexpected_keys:
        raise RuntimeError(
            "Error(s) in loading state_dict. Unexpected key(s) in state_dict: "
            + ", ".join(f'"{k}"' for k in unexpected_keys) + ". ")
