from . import op_categories  # noqa: F401
from .op_categories import (  # noqa: F401
    BANNED_FUNCS, CASTS, FP16_FUNCS, FP32_FUNCS, SEQUENCE_CASTS)
