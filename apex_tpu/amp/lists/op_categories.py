"""Casting-policy tables for the O1 trace-time policy, in terms of
``apex_tpu.nn.functional`` op names.

Mirrors the reference tables (apex/amp/lists/functional_overrides.py and
torch_overrides.py) translated to this framework's op vocabulary:

* WIDEN-to-half (MXU-friendly): convolutions and matmul-shaped ops — the
  reference's FP16_FUNCS (functional_overrides.py:18-27,
  torch_overrides.py:7-27).
* Keep-float (stability): softmax/normalization/losses, transcendental
  pointwise ops and reductions — FP32_FUNCS (functional_overrides.py:29-68,
  torch_overrides.py:29-61).
* PROMOTE: multi-arg ops cast to the widest input type — CASTS
  (torch_overrides.py:86-108).
* SEQUENCE_CASTS: cat/stack (torch_overrides.py:112-115).
* BANNED: binary_cross_entropy (functional_overrides.py:70-80) — raises under
  O1 unless allow_banned.

There is no CUDA-version-dependent bmm placement: the MXU handles batched
matmul in half natively, so ``bmm`` is always on the half list.
"""

FP16_FUNCS = [
    "conv1d", "conv2d", "conv3d",
    "conv_transpose1d", "conv_transpose2d", "conv_transpose3d",
    "linear", "matmul", "mm", "bmm", "addmm", "einsum", "dot_general",
    "prelu",
    # apex modules registered via amp.half_function in the reference
    "mlp",  # apex/mlp/mlp.py:22
]

FP32_FUNCS = [
    # pointwise transcendentals
    "softplus", "softmin", "log_softmax", "softmax", "gelu",
    "acos", "asin", "cosh", "erfinv", "exp", "expm1",
    "log", "log10", "log2", "reciprocal", "rsqrt", "sinh", "tan", "pow",
    # normalization
    "layer_norm", "group_norm", "instance_norm", "batch_norm",
    "local_response_norm", "normalize", "cosine_similarity",
    # losses
    "cross_entropy", "nll_loss", "l1_loss", "mse_loss", "smooth_l1_loss",
    "kl_div", "poisson_nll_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "margin_ranking_loss", "multilabel_margin_loss",
    "multilabel_soft_margin_loss", "multi_margin_loss",
    "binary_cross_entropy_with_logits", "soft_margin_loss",
    "triplet_margin_loss", "ctc_loss",
    # reductions
    "cumprod", "cumsum", "dist", "norm", "prod", "std", "sum", "var",
    "renorm",
]

CASTS = [
    "addcdiv", "addcmul", "atan2", "cross", "bilinear", "dot",
    "add", "div", "mul",
    "eq", "equal", "ge", "gt", "le", "lt", "ne",
]

SEQUENCE_CASTS = ["cat", "stack", "concatenate"]

BANNED_FUNCS = [
    ("binary_cross_entropy",
     ("\namp does not work out-of-the-box with `binary_cross_entropy`. "
      "It requires that the output of the previous function be already a "
      "float tensor. \n\nMost models have a Sigmoid right before BCELoss. "
      "In that case, you can use\n    binary_cross_entropy_with_logits\nto "
      "combine Sigmoid+BCELoss into a single layer that is compatible with "
      "amp.\nAnother option is to add\n    amp.register_float_function(...)\n"
      "before calling `amp.init()`.\nIf you _really_ know what you are "
      "doing, you can disable this error by passing allow_banned=True to "
      "`amp.init()`.")),
]
