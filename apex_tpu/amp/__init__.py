"""Mixed-precision core (reference: apex/amp/).

Public surface: initialize, scale_loss (added with the training facade),
state_dict/load_state_dict, master_params, LossScaler, the O1 registry API,
and the trace-time policy engine.
"""
from ._amp_state import _amp_state, master_params, maybe_print  # noqa: F401
from .frontend import (  # noqa: F401
    Properties, initialize, load_state_dict, opt_levels, resolve_dtype,
    set_default_half_dtype, get_default_half_dtype, state_dict)
from .policy import (  # noqa: F401
    CastPolicy, apply_op_policy, autocast, current_policy, disable_casts,
    float_function, half_function, promote_function, register_float_function,
    register_half_function, register_promote_function)
from .handle import AmpHandle, NoOpHandle, init, scale_loss  # noqa: F401
from .opt import OptimWrapper  # noqa: F401
from .scaler import (  # noqa: F401
    LossScaler, ScalerState, init_scaler_state, unscale_grads,
    unscale_with_stashed_grads, update_scale_state)
from . import lists  # noqa: F401
