"""Legacy old-API optimizer wrapper (reference: apex/amp/opt.py:9-103).

``handle = amp.init(...); optimizer = handle.wrap_optimizer(opt, num_loss=N)``
— the pre-``amp.initialize`` multi-loss API.  Each loss gets its own dynamic
scaler; ``with optimizer.scale_loss(loss) as scaled: scaled.backward()`` per
loss, then one ``optimizer.step()`` which is skipped if ANY loss overflowed.

Mechanics differ from the reference only where the array model forces it:
grads are immutable jnp arrays hanging off ``Parameter.grad`` (filled by the
tape's ``backward``), so "save out current grad accumulation" is a list copy
of references rather than ``.detach().clone()``, and the in-place unscale is
a functional rebind of ``p.grad``.
"""
from __future__ import annotations

import contextlib

from ._amp_state import master_params, maybe_print
from .scaler import LossScaler


class OptimWrapper:
    def __init__(self, optimizer, amp_handle, num_loss, loss_scale="dynamic"):
        self._optimizer = optimizer
        self._amp_handle = amp_handle
        self._num_loss = num_loss
        self._loss_idx = 0
        self._skip_next = [False] * num_loss
        # per-loss scalers honor the handle's loss_scale (the reference
        # hardcodes 'dynamic' here, opt.py:16, silently ignoring a static
        # scale passed to amp.init)
        self._loss_scaler = [LossScaler(loss_scale) for _ in range(num_loss)]

    @contextlib.contextmanager
    def scale_loss(self, loss):
        if not self._amp_handle.is_active():
            yield loss
            return

        # With multiple losses per optimizer the running grad accumulation
        # must be saved out before this loss's backward: once the grads mix
        # we can no longer unscale this particular loss
        # (reference opt.py:24-35).
        cached_grads = []
        if self._loss_idx > 0:
            for p in master_params(self._optimizer):
                cached_grads.append(p.grad)
                p.grad = None

        loss_scale = self._cur_loss_scaler().loss_scale()
        yield loss.float() * loss_scale

        self._cur_loss_scaler().clear_overflow_state()
        params = [p for p in master_params(self._optimizer)]
        live = [p for p in params if p.grad is not None]
        if live:
            new_grads = self._cur_loss_scaler().unscale(
                [p.grad for p in live], [p.grad for p in live],
                loss_scale, models_are_masters=True)
            for p, g in zip(live, new_grads):
                p.grad = g
        self._skip_next[self._loss_idx] = \
            self._cur_loss_scaler().update_scale()
        self._loss_idx += 1

        if len(cached_grads) > 0:
            for p, cached in zip(params, cached_grads):
                if cached is not None:
                    p.grad = cached if p.grad is None else p.grad + cached

    def _cur_loss_scaler(self):
        assert 0 <= self._loss_idx < self._num_loss
        return self._loss_scaler[self._loss_idx]

    def step(self, closure=None):
        if not self._amp_handle.is_active():
            return self._optimizer.step(closure=closure)

        self._loss_idx = 0

        for group in self._optimizer.param_groups:
            for p in group["params"]:
                self._amp_handle.remove_cache(p)

        if closure is not None:
            raise NotImplementedError(
                "The `closure` argument is unsupported by the amp "
                "optimizer wrapper.")
        if any(self._skip_next):
            maybe_print("Gradient overflow, skipping update")
            self._skip_next = [False] * self._num_loss
        else:
            return self._optimizer.step()

    # Forward any attribute lookups to the wrapped optimizer
    # (reference opt.py:79-103).
    def __getattr__(self, attr):
        return getattr(self._optimizer, attr)

    def __repr__(self):
        return self._optimizer.__repr__()

    def state_dict(self):
        return self._optimizer.state_dict()

    def load_state_dict(self, state_dict):
        return self._optimizer.load_state_dict(state_dict)

    def zero_grad(self):
        return self._optimizer.zero_grad()

    def add_param_group(self, param_group):
        return self._optimizer.add_param_group(param_group)
