"""O1 autocast as a *trace-time* dtype policy.

The reference implements O1 by monkey-patching torch namespaces with cast
wrappers (apex/amp/amp.py:68-177, wrap.py).  Under JAX there is no eager
dispatch to intercept: every op in the model runs during a single trace.  The
idiomatic equivalent — producing the same observable dtype behavior — is a
policy object consulted by every ``apex_tpu.nn.functional`` op while tracing:

* ops on the half list (convs, matmuls → MXU) cast float args to the policy's
  half dtype (reference whitelist, amp.py:90-95);
* ops on the float list (softmax/norms/losses/transcendentals) cast float args
  to fp32 (blacklist, amp.py:96-101);
* promote ops cast all float args to the widest participating float type
  (wrap.py:65-90), sequence ops likewise over their element list;
* banned ops raise (amp.py:164-171) unless ``allow_banned``.

The user registry API (``register_half_function`` etc., amp.py:30-64) is kept:
it wraps functions on arbitrary Python modules with cast wrappers driven by
the active policy.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from . import lists
from ._amp_state import maybe_print

_FLOAT_DTYPES = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def _is_float_array(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast_tree(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and _is_float_array(x) and x.dtype != dtype:
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


_WIDTH = {jnp.dtype(jnp.float16): 0, jnp.dtype(jnp.bfloat16): 0,
          jnp.dtype(jnp.float32): 1, jnp.dtype(jnp.float64): 2}


def widest_float_dtype(tree):
    """The widest participating float dtype (wrap.py:65-78's promotion rule:
    fp16 collections stay fp16, anything mixed promotes to fp32)."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if _is_float_array(x)]
    if not leaves:
        return None
    dtypes = {jnp.dtype(x.dtype) for x in leaves}
    if len(dtypes) == 1:
        return next(iter(dtypes)).type
    width = max(_WIDTH.get(d, 1) for d in dtypes)
    if width == 0:  # mixed half types (fp16 + bf16): promote to fp32
        return jnp.float32
    return jnp.float64 if width == 2 else jnp.float32


class CastPolicy:
    """The active-cast configuration for one amp session."""

    def __init__(self, half_dtype=jnp.float16, enabled: bool = True,
                 allow_banned: bool = False, verbose: bool = False):
        self.half_dtype = jnp.dtype(half_dtype).type
        self.enabled = enabled
        self.allow_banned = allow_banned
        self.verbose = verbose
        self.user_half = set()
        self.user_float = set()
        self.user_promote = set()

    # -- category lookup ---------------------------------------------------
    def category_of(self, op_name: str) -> Optional[str]:
        if op_name in self.user_half:
            return "half"
        if op_name in self.user_float:
            return "float"
        if op_name in self.user_promote:
            return "promote"
        for name, _msg in lists.BANNED_FUNCS:
            if op_name == name:
                return "banned"
        if op_name in lists.FP16_FUNCS:
            return "half"
        if op_name in lists.FP32_FUNCS:
            return "float"
        if op_name in lists.CASTS:
            return "promote"
        if op_name in lists.SEQUENCE_CASTS:
            return "sequence"
        return None

    # -- the cast itself ---------------------------------------------------
    def cast_args(self, op_name: str, args, kwargs=None):
        """Apply this policy's cast for ``op_name`` to (args, kwargs)."""
        kwargs = {} if kwargs is None else kwargs
        cat = self.category_of(op_name)
        if cat is None:
            return args, kwargs
        if cat == "banned":
            if not self.allow_banned:
                msg = dict(lists.BANNED_FUNCS)[op_name]
                raise NotImplementedError(msg)
            return args, kwargs
        if cat == "half":
            dtype = self.half_dtype
        elif cat == "float":
            dtype = jnp.float32
        else:  # promote / sequence
            dtype = widest_float_dtype((args, kwargs))
            if dtype is None:
                return args, kwargs
        if self.verbose:
            maybe_print(f"amp: casting args of {op_name} to "
                        f"{jnp.dtype(dtype).name}")
        return _cast_tree(args, dtype), _cast_tree(kwargs, dtype)


# ---------------------------------------------------------------------------
# Active-policy stack
# ---------------------------------------------------------------------------

_policy_stack: list = []


def current_policy() -> Optional[CastPolicy]:
    """The innermost active policy, or None when casts are disabled."""
    return _policy_stack[-1] if _policy_stack else None


def casts_disabled() -> bool:
    """True inside an explicit ``disable_casts`` scope (stack top is None).
    Distinct from an *empty* stack (no scope at all): the ambient-policy
    fallback must honor the former but not the latter."""
    return bool(_policy_stack) and _policy_stack[-1] is None


@contextlib.contextmanager
def autocast(policy: Optional[CastPolicy]):
    """Activate ``policy`` for the duration (used by amp-initialized model
    forwards and user code).  ``autocast(None)`` == the reference handle's
    ``disable_casts`` (handle.py:163-167)."""
    _policy_stack.append(policy)
    try:
        yield policy
    finally:
        _policy_stack.pop()


disable_casts = functools.partial(autocast, None)


def apply_op_policy(op_name: str, args, kwargs=None):
    """Hook called by apex_tpu.nn.functional ops: cast per the active policy."""
    pol = current_policy()
    if pol is None or not pol.enabled:
        return args, ({} if kwargs is None else kwargs)
    return pol.cast_args(op_name, args, kwargs)


# ---------------------------------------------------------------------------
# User registry / decorator API (reference amp.py:30-64)
# ---------------------------------------------------------------------------

def _wrapped(fn, op_name: str):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        args, kwargs = apply_op_policy(op_name, args, kwargs)
        return fn(*args, **kwargs)
    wrapper._amp_registered = op_name
    return wrapper


def _register(user_set_name: str, module, name: str):
    for pol in _policy_stack:
        if pol is not None:
            getattr(pol, user_set_name).add(name)
    _pending_registrations.append((user_set_name, name))
    setattr(module, name, _wrapped(getattr(module, name), name))


# registrations made before amp.initialize() creates the session policy are
# replayed onto it (the reference requires registration before amp.init too,
# amp.py:30-42)
_pending_registrations: list = []


def replay_registrations(policy: CastPolicy):
    for user_set_name, name in _pending_registrations:
        getattr(policy, user_set_name).add(name)


def register_half_function(module, name):
    _register("user_half", module, name)


def register_float_function(module, name):
    _register("user_float", module, name)


def register_promote_function(module, name):
    _register("user_promote", module, name)


def half_function(fn):
    """Decorator: run ``fn`` with float args cast to the policy half dtype."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol is not None and pol.enabled:
            args = _cast_tree(args, pol.half_dtype)
            kwargs = _cast_tree(kwargs, pol.half_dtype)
        return fn(*args, **kwargs)
    return wrapper


def float_function(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol is not None and pol.enabled:
            args = _cast_tree(args, jnp.float32)
            kwargs = _cast_tree(kwargs, jnp.float32)
        return fn(*args, **kwargs)
    return wrapper


def promote_function(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol is not None and pol.enabled:
            dtype = widest_float_dtype((args, kwargs))
            if dtype is not None:
                args = _cast_tree(args, dtype)
                kwargs = _cast_tree(kwargs, dtype)
        return fn(*args, **kwargs)
    return wrapper
