"""Applies the chosen amp Properties to models/optimizers
(reference: apex/amp/_initialize.py:145-263).

The full implementation lands with the nn/training facade; until then
``amp.initialize`` fails loudly here instead of deep in a cast path.
"""
from __future__ import annotations


def _initialize(models, optimizers, properties, num_losses=1,
                cast_model_outputs=None):
    raise NotImplementedError(
        "amp.initialize requires the apex_tpu.nn model facade, which is "
        "being added in the next milestone of this build.  The functional "
        "amp API (apex_tpu.amp.LossScaler, init_scaler_state, unscale_grads, "
        "update_scale_state, autocast/CastPolicy) is available now.")
