"""Applies the chosen amp Properties to models/optimizers
(reference: apex/amp/_initialize.py:145-263).

TPU adaptations:
* model casting operates on apex_tpu.nn.Module parameters (``convert_network``
  == cast all float params except ``_BatchNorm`` modules, mirroring
  fp16util.py:60-70);
* the forward patch is implemented by tagging the model with
  ``_amp_input_cast_dtype`` / ``_amp_output_cast_dtype`` / ``_amp_policy``
  attributes that the autograd tape honors on every call — same observable
  behavior as patching ``model.forward``, but the casts are recorded in the
  tape program so backward's re-execution sees identical dtypes;
* O1 installs a trace-time CastPolicy instead of monkey-patching torch.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.modules import Module, _BatchNorm
from ._amp_state import _amp_state, warn_or_err
from ._process_optimizer import _process_optimizer
from .policy import CastPolicy, replay_registrations
from .scaler import LossScaler


def check_models(models):
    for model in models:
        if type(model).__name__ == "DistributedDataParallel" and \
                not type(model).__module__.startswith("apex_tpu"):
            raise RuntimeError(
                "Incoming model is an instance of an unsupported parallel "
                "wrapper. apex_tpu.parallel.DistributedDataParallel must be "
                "applied AFTER amp.initialize.")
        if not isinstance(model, Module):
            raise RuntimeError("amp.initialize expects apex_tpu.nn.Module "
                               f"models, got {type(model)}")


def check_params_fp32(models):
    for model in models:
        for name, param in model.named_parameters():
            if param.requires_grad and not jnp.issubdtype(
                    param.dtype, jnp.floating):
                continue
            if param.requires_grad and \
                    jnp.dtype(param.dtype) != jnp.dtype(jnp.float32):
                warn_or_err(
                    f"Found param {name} with type {param.dtype}, expected "
                    "float32.  When using amp.initialize, you do not need to "
                    "call .half()/.bfloat16() on your model before passing "
                    "it, no matter what optimization level you choose.")


def check_optimizers(optimizers):
    for optim in optimizers:
        if hasattr(optim, "_amp_stash"):
            raise RuntimeError(
                "An incoming optimizer has already been processed by "
                "amp.initialize; reuse is not supported.")


def convert_network(model: Module, dtype):
    """Cast float params and buffers to ``dtype``, skipping batchnorm modules
    entirely (params AND running stats stay fp32 — reference fp16util.py:60-70
    via _initialize.py:176-179)."""
    model._cast_params(dtype, predicate=lambda m: not isinstance(m,
                                                                 _BatchNorm))
    return model


def _patch_state_dict_fp32(model: Module):
    """O2StateDictHook analogue (reference _initialize.py:133-142,207-210):
    model.state_dict() returns fp32 views of half params."""
    old_state_dict = model.state_dict

    def fp32_state_dict():
        sd = old_state_dict()
        for k, v in sd.items():
            if jnp.issubdtype(v.dtype, jnp.floating) and \
                    jnp.dtype(v.dtype) != jnp.dtype(jnp.float32):
                sd[k] = v.astype(jnp.float32)
        return sd

    model.state_dict = fp32_state_dict


def _initialize(models, optimizers, properties, num_losses=1,
                cast_model_outputs=None):
    from ..optimizers.base import Optimizer
    from ..parallel.LARC import LARC

    optimizers_was_list = False
    if isinstance(optimizers, (Optimizer, LARC)):
        optimizers = [optimizers]
    elif optimizers is None:
        optimizers = []
    elif isinstance(optimizers, list):
        optimizers_was_list = True
        check_optimizers(optimizers)
    else:
        raise TypeError("optimizers must be either a single optimizer or a "
                        "list of optimizers.")

    if isinstance(models, Module):
        models_was_list = False
        models = [models]
    elif isinstance(models, list):
        models_was_list = True
    else:
        raise TypeError("models must be either a single model or a list of "
                        "models.")

    check_models(models)
    if not _amp_state.allow_incoming_model_not_fp32:
        check_params_fp32(models)

    if properties.cast_model_type:
        if properties.keep_batchnorm_fp32:
            for model in models:
                convert_network(model, properties.cast_model_type)
        else:
            for model in models:
                model.to(properties.cast_model_type)

        for model in models:
            model._amp_input_cast_dtype = properties.cast_model_type
            model._amp_output_cast_dtype = (
                cast_model_outputs if cast_model_outputs is not None
                else jnp.float32)
            _patch_state_dict_fp32(model)
    elif cast_model_outputs is not None:
        for model in models:
            model._amp_output_cast_dtype = cast_model_outputs

    for i, optimizer in enumerate(optimizers):
        optimizers[i] = _process_optimizer(optimizer, properties)

    _amp_state.loss_scalers = []
    for _ in range(num_losses):
        _amp_state.loss_scalers.append(
            LossScaler(properties.loss_scale,
                       min_loss_scale=getattr(_amp_state, "min_loss_scale",
                                              None),
                       max_loss_scale=getattr(_amp_state, "max_loss_scale",
                                              2.0 ** 24)))

    if properties.patch_torch_functions:
        from . import frontend
        policy = CastPolicy(
            half_dtype=frontend.get_default_half_dtype(),
            enabled=True,
            verbose=(_amp_state.verbosity == 2))
        replay_registrations(policy)
        # The reference patches torch *globally* (amp.py:68-177), so every
        # module — criterion included — sees the casts.  The tape-level
        # equivalent: an ambient policy applied to every Module call that
        # has no explicit tags (autograd.record_module_call).
        _amp_state.handle = policy
        _amp_state.ambient_policy = policy
        for model in models:
            model._amp_policy = policy
        # the optimizer step itself must not be cast (reference
        # _initialize.py:239-246) — our optimizers run on raw arrays outside
        # any policy scope, so nothing to patch.

    if optimizers_was_list:
        return (models if models_was_list else models[0]), optimizers
    if models_was_list:
        return models if len(optimizers) == 0 else (models, optimizers[0])
    return models[0] if len(optimizers) == 0 else (models[0], optimizers[0])
