"""Loss scaling (reference: apex/amp/scaler.py).

Two layers:

* a **functional core** (`ScalerState`, `update_scale_state`, `unscale_grads`)
  that lives entirely on device so a whole train step — unscale, overflow
  check, conditional skip, scale update — compiles into one XLA program with
  **zero** host round-trips (the reference pays one D2H sync per step,
  scaler.py:197-200; we only sync when the user *asks* for the scale);
* a **stateful `LossScaler`** with the reference's exact API and dynamics:
  dynamic scaling starts at ``min(max_loss_scale, 2**16)``, halves on
  overflow (clamped to ``min_loss_scale``), and doubles after
  ``scale_window=2000`` consecutive clean steps, clamped to
  ``max_loss_scale=2**24`` (scaler.py:38-56,197-217).
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from ..runtime import executor as _executor

_f32 = jnp.float32


class ScalerState(NamedTuple):
    """On-device dynamic-loss-scale state."""
    loss_scale: jax.Array   # f32 scalar
    unskipped: jax.Array    # i32 scalar — clean steps since last change
    overflow: jax.Array     # i32 scalar — this step's noop flag


def init_scaler_state(loss_scale, init_scale=2.0 ** 16,
                      max_loss_scale=2.0 ** 24) -> ScalerState:
    if loss_scale == "dynamic":
        scale = min(max_loss_scale, init_scale)
    else:
        scale = float(loss_scale)
    return ScalerState(jnp.asarray(scale, _f32), jnp.zeros((), jnp.int32),
                       jnp.zeros((), jnp.int32))


def update_scale_state(state: ScalerState, *, dynamic: bool,
                       scale_factor: float = 2.0,
                       scale_window: int = 2000,
                       min_loss_scale: Optional[float] = None,
                       max_loss_scale: float = 2.0 ** 24):
    """Pure version of LossScaler.update_scale (scaler.py:197-215).

    Returns (new_state, should_skip).  ``should_skip`` is a device bool —
    feed it to ``jnp.where``/``lax.cond`` to skip the optimizer step without
    leaving the compiled program (the reference instead monkey-patches
    ``optimizer.step``, handle.py:128-154; observable effect is identical).
    """
    overflow = state.overflow > 0
    if not dynamic:
        # static scale: never skips, never changes (reference: _has_overflow
        # is only ever read for dynamic scalers)
        new_unskipped = state.unskipped + 1
        return ScalerState(state.loss_scale, new_unskipped,
                           jnp.zeros((), jnp.int32)), jnp.zeros((), jnp.bool_)

    halved = state.loss_scale / scale_factor
    if min_loss_scale is not None:
        halved = jnp.maximum(jnp.asarray(min_loss_scale, _f32), halved)
    scale = jnp.where(overflow, halved, state.loss_scale)
    unskipped = jnp.where(overflow, 0, state.unskipped + 1)

    grow = unskipped == scale_window
    scale = jnp.where(grow,
                      jnp.minimum(jnp.asarray(max_loss_scale, _f32),
                                  scale * scale_factor), scale)
    unskipped = jnp.where(grow, 0, unskipped)
    return ScalerState(scale, unskipped, jnp.zeros((), jnp.int32)), overflow


def unscale_grads(state: ScalerState, model_grads: Sequence[jax.Array],
                  master_dtypes=None, check_overflow: bool = True,
                  scale_override=None):
    """master_grad = model_grad / loss_scale, flagging non-finites.

    Functional analogue of LossScaler.unscale (scaler.py:76-124): the whole
    unscale + overflow sweep runs as ONE cached executable
    (``executor.unscale``) instead of eager per-tensor dispatches.
    Returns (new_state, master_grads).
    """
    scale = state.loss_scale if scale_override is None \
        else jnp.asarray(scale_override, _f32)
    inv = 1.0 / scale
    dts = [g.dtype if master_dtypes is None else master_dtypes[i]
           for i, g in enumerate(model_grads)]
    flag, masters = _executor.unscale(
        state.overflow, list(model_grads), dts, inv,
        check_overflow=check_overflow)
    return ScalerState(state.loss_scale, state.unskipped, flag), masters


def unscale_with_stashed_grads(state: ScalerState, model_grads, stashed_grads,
                               scale_override=None):
    """Grad accumulation across backward passes: out = (1/scale)*new + 1*stashed
    via the fused axpby (reference scaler.py:152-189).  Returns
    (new_state, master_grads)."""
    out_scale = 1.0
    if scale_override is not None:
        # (grads_have_scale, stashed_have_scale, out_scale) triple, as in
        # scaler.py:160-165
        grads_have_scale, stashed_have_scale, out_scale = scale_override
    else:
        grads_have_scale, stashed_have_scale = state.loss_scale, 1.0
    flag, masters = _executor.unscale_with_stashed(
        state.overflow, list(model_grads), list(stashed_grads),
        out_scale / grads_have_scale, out_scale / stashed_have_scale)
    return ScalerState(state.loss_scale, state.unskipped, flag), masters


class LossScaler:
    """Stateful facade with the reference's API (apex/amp/scaler.py:33).

    Holds a `ScalerState` of device arrays; `loss_scale()` performs the one
    host readback (only when called — e.g. for printing or `amp.state_dict`).
    """
    warned_no_fused_kernel = False
    warned_unscaling_non_fp32_grad = False
    has_fused_kernel = True

    def __init__(self, loss_scale, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_loss_scale=None,
                 max_loss_scale=2.0 ** 24):
        self.dynamic = loss_scale == "dynamic"
        #: known-without-sync scale for static scalers (None when dynamic)
        self.static_scale = None if self.dynamic else float(loss_scale)
        self._state = init_scaler_state(loss_scale, init_scale, max_loss_scale)
        self._max_loss_scale = max_loss_scale
        self._min_loss_scale = min_loss_scale
        self._scale_seq_len = scale_window
        self._scale_factor = scale_factor

    # -- state plumbing ----------------------------------------------------
    @property
    def state(self) -> ScalerState:
        return self._state

    @state.setter
    def state(self, s: ScalerState):
        self._state = s

    # reference-compat accessors (frontend.state_dict reads these)
    def loss_scale(self):
        return float(self._state.loss_scale)

    @property
    def device_scale(self):
        """The loss scale as a device scalar — use this on per-step paths;
        ``loss_scale()`` is a host readback (one D2H sync)."""
        return self._state.loss_scale

    @property
    def _unskipped(self):
        return int(self._state.unskipped)

    @_unskipped.setter
    def _unskipped(self, v):
        self._state = self._state._replace(unskipped=jnp.asarray(v, jnp.int32))

    @property
    def _loss_scale(self):
        return float(self._state.loss_scale)

    @_loss_scale.setter
    def _loss_scale(self, v):
        self._state = self._state._replace(loss_scale=jnp.asarray(v, _f32))

    # -- reference API -----------------------------------------------------
    def clear_overflow_state(self):
        self._state = self._state._replace(overflow=jnp.zeros((), jnp.int32))

    def unscale(self, model_grads, master_grads, unused_scale=None,
                models_are_masters=False, scale_override=None):
        """Returns the new master grads (functional; callers rebind)."""
        self._state, masters = unscale_grads(
            self._state, list(model_grads),
            master_dtypes=[m.dtype for m in master_grads],
            scale_override=scale_override)
        return masters

    def unscale_with_stashed(self, model_grads, stashed_master_grads,
                             master_grads, scale_override=None):
        self._state, masters = unscale_with_stashed_grads(
            self._state, model_grads, stashed_master_grads, scale_override)
        return masters

    def update_scale(self):
        """One host sync, as in the reference (scaler.py:197-200): returns a
        Python bool ``should_skip``."""
        new_state, should_skip = update_scale_state(
            self._state, dynamic=self.dynamic,
            scale_factor=self._scale_factor,
            scale_window=self._scale_seq_len,
            min_loss_scale=self._min_loss_scale,
            max_loss_scale=self._max_loss_scale)
        skip = bool(should_skip)
        self._state = new_state
        return skip
