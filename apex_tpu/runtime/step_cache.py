"""Single-executable, donated step-program cache for the eager optimizer
surface (``amp.initialize`` + ``optimizer.step()`` — the path the imagenet /
dcgan / simple examples drive).

Before this cache the eager surface dispatched one jitted executable per
param-group × dtype bucket with static hyperparameters and no buffer
donation: every step re-allocated params + both Adam moments (3× param
memory churn) and any lr/wd/beta schedule retraced the whole update — the
per-step weight-update overhead that arxiv 2004.13336 identifies as a
first-order cost of data-parallel training.  Here the ENTIRE update — grad
unscale + overflow check (``amp/scaler.py``), per-group optimizer math for
all groups and dtype buckets, conditional skip via ``lax.cond``, and the
dynamic-loss-scale update — compiles into ONE XLA executable per optimizer:

* keyed on (pytree structure, leaf shapes/dtypes, static config) — the same
  things ``jax.jit`` retraces on, so cache misses == XLA compiles and
  ``stats()`` makes retrace regressions observable;
* ``donate_argnums`` on params, optimizer state and scaler state — XLA
  writes the new params/moments into the old buffers (``tf.aliasing_output``
  in the lowered HLO), so steady-state optimizer stepping allocates nothing.
  Donation follows the "auto" policy: on for tpu/gpu, off for cpu (XLA cpu
  accepts donate_argnums but degrades it to defensive copies — measured 2×
  step time; see :func:`set_donation`).  Consequence when on: any reference
  to a PRE-step ``p.data`` (or moment array) a caller stashed is
  invalidated by the step — copy first if you need it;
* all scalar hyperparameters (lr, betas, eps, weight_decay, step) enter as
  traced device scalars, so lr/wd/beta schedules never recompile.

The stateful optimizers (``apex_tpu.optimizers``, ``contrib.optimizers``)
collect their ``param_groups`` into pure pytrees and dispatch here; the amp
hooks (``_process_optimizer``, ``handle.scale_loss``) route the unscale /
master→model copy / deferred scale update through the same cache.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp

from ..observe import registry as _obs

#: opt-in ``span("dispatch")`` around every eager step-cache dispatch.
#: Off by default: the eager optimizer hot path is microbenchmarked
#: (``bench.py --opt-microbench``) and a per-step span event would be a
#: measurable fraction of a small fused step; the dispatch *counters*
#: always flow through the observe registry regardless.
_DISPATCH_SPANS = False


def set_dispatch_spans(enable: bool) -> None:
    """Enable/disable ``span("dispatch")`` around eager cache dispatches."""
    global _DISPATCH_SPANS
    _DISPATCH_SPANS = bool(enable)


def _leaf_sig(leaf):
    return (tuple(leaf.shape), jnp.dtype(leaf.dtype).name)


def signature(tree):
    """Hashable (treedef, leaf shapes/dtypes) key for an argument pytree —
    exactly what jit retraces on (all leaves enter strongly typed)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, tuple(_leaf_sig(l) for l in leaves))


def _example_avals(tree):
    return jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(tuple(l.shape), jnp.dtype(l.dtype)),
        tree)


class StepCache:
    """Compiled step-program cache with compile/dispatch counters.

    One entry per (kind, static config, argument signature); entries hold
    the jitted callable plus a ShapeDtypeStruct example tree so callers
    (tests, tooling) can re-lower a cached program without live arrays.
    LRU-capped so dead parameter sets cannot pin executables forever.
    """

    _TOP_COUNTERS = ("compiles", "cache_hits", "dispatches",
                     "multi_tensor_calls")
    _KIND_COUNTERS = ("compiles", "cache_hits", "dispatches")

    def __init__(self, cap: int = 128, metrics_prefix: str = "step_cache."):
        self._cap = cap
        self._prefix = metrics_prefix
        self._registry = _obs.get_registry()
        self._lock = threading.RLock()
        self._programs: OrderedDict = OrderedDict()
        self.reset_stats()

    # -- stats -------------------------------------------------------------
    # Counters live in the apex_tpu.observe registry (names
    # ``step_cache.<counter>`` / ``step_cache.kind.<kind>.<counter>``);
    # ``stats()`` reconstructs the historical dict shape from them so the
    # public surface — and every test pinned to it — is unchanged.

    def reset_stats(self):
        self._registry.remove(self._prefix)

    def _bump(self, name, kind=None):
        self._registry.counter(self._prefix + name).inc()
        if kind is not None:
            self._registry.counter(
                f"{self._prefix}kind.{kind}.{name}").inc()

    def stats(self) -> dict:
        """Counters for regression tracking.

        ``compiles`` is the analogue of the reference's kernel-*build* cost
        (one per new program shape), ``dispatches`` of its per-step kernel
        *launch* count — except one dispatch here covers what the CUDA
        reference spreads over dozens of ``multi_tensor_*`` launches.
        ``multi_tensor_calls`` counts eager multi-tensor op invocations for
        a direct launch-count comparison with the reference.
        """
        counters = self._registry.snapshot()["counters"]
        out = {n: counters.get(self._prefix + n, 0)
               for n in self._TOP_COUNTERS}
        by_kind: dict = {}
        kind_prefix = self._prefix + "kind."
        for full, value in counters.items():
            if not full.startswith(kind_prefix):
                continue
            kind, _, cname = full[len(kind_prefix):].rpartition(".")
            if kind and cname in self._KIND_COUNTERS:
                by_kind.setdefault(
                    kind, {n: 0 for n in self._KIND_COUNTERS})[cname] = value
        with self._lock:
            out["programs"] = len(self._programs)
        out["by_kind"] = by_kind
        return out

    # -- cache -------------------------------------------------------------
    def program(self, kind: str, static_key, args, build):
        """Return the compiled program for ``args``, building on a miss.

        ``static_key`` must be hashable and capture every Python-level value
        the built program closes over; ``args`` is the exact argument tuple
        the program will be called with (its structure + shapes/dtypes
        complete the key).
        """
        key = (kind, static_key, signature(args))
        with self._lock:
            entry = self._programs.pop(key, None)
            if entry is not None:
                self._programs[key] = entry     # pop + reinsert = LRU
                self._bump("cache_hits", kind)
                return entry["fn"]
        fn = build()
        with self._lock:
            while len(self._programs) >= self._cap:
                self._programs.popitem(last=False)
            self._programs[key] = {"kind": kind, "fn": fn,
                                   "example": _example_avals(args)}
            self._bump("compiles", kind)
        return fn

    def entries(self):
        """Snapshot of cached programs: [{kind, fn, example}] — ``example``
        is a ShapeDtypeStruct tree accepted by ``fn.lower(*example)``."""
        with self._lock:
            return [dict(e) for e in self._programs.values()]

    def clear(self):
        with self._lock:
            self._programs.clear()


#: process-global cache shared by every optimizer / amp hook
step_cache = StepCache()


def set_donation(mode):
    """Set the donation policy: True, False, or "auto" (default).

    Delegate onto :data:`apex_tpu.runtime.executor.donation` — the one
    :class:`~apex_tpu.runtime.executor.DonationPolicy` every surface
    shares (the policy used to be re-derived here, in training/step.py
    and in the amp handle).  Kept under the historical name.
    """
    from . import executor
    executor.donation.set(mode)


def donation_enabled() -> bool:
    from . import executor
    return executor.donation.enabled


def stats() -> dict:
    return step_cache.stats()


def kind_stats(kind: str) -> dict:
    """One kind's ``{compiles, cache_hits, dispatches}`` (zeros if the
    kind never dispatched) — the serve engine's recompile-free-decode
    bound reads ``kind_stats("decode_step")["compiles"]`` and asserts
    it stays <= the bucket count after warmup."""
    return stats()["by_kind"].get(
        kind, {n: 0 for n in StepCache._KIND_COUNTERS})


def reset_stats():
    step_cache.reset_stats()


def clear():
    step_cache.clear()


def record_multi_tensor_call():
    step_cache._bump("multi_tensor_calls")


def static_plan_key(plan):
    """Normalize a ``parallel.auto.Plan`` (or None) into the hashable
    tuple program keys embed — the historical ``(dp, tp, sp, zero_stage,
    accum, chunked_loss)`` 6-tuple, plus tagged string segments
    (``"pp4"``, ``"micro8"``, ``"remat=selective"``, ``"ep8"``,
    ``"offopt=1"``, ``"offact=0.5"``) appended only when a v3 axis is
    non-default, so pre-v3 keys are unchanged.  ``plan_from_key``
    inverts it.  Threading the plan through the STATIC key keeps
    compiled executables per-plan observables: two plans that would
    otherwise collide on signature (same shapes, different mesh
    factorization driven by the wrapper) never share a program entry,
    and ``stats()['by_kind']`` stays meaningful under ``parallel=``.
    None (an unplanned step) passes through as None."""
    if plan is None:
        return None
    return tuple(plan.key())


# The whole-optimizer / amp step programs that used to live here
# (optimizer_step, optimizer_step_with_scaler, unscale,
# unscale_with_stashed, master_to_model) moved to
# ``apex_tpu.runtime.executor`` — the one dispatch choke point both the
# eager and the fused surface now submit Program descriptors to.  This
# module keeps only the cache itself and its stats surface.
