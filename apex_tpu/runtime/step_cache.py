"""Single-executable, donated step-program cache for the eager optimizer
surface (``amp.initialize`` + ``optimizer.step()`` — the path the imagenet /
dcgan / simple examples drive).

Before this cache the eager surface dispatched one jitted executable per
param-group × dtype bucket with static hyperparameters and no buffer
donation: every step re-allocated params + both Adam moments (3× param
memory churn) and any lr/wd/beta schedule retraced the whole update — the
per-step weight-update overhead that arxiv 2004.13336 identifies as a
first-order cost of data-parallel training.  Here the ENTIRE update — grad
unscale + overflow check (``amp/scaler.py``), per-group optimizer math for
all groups and dtype buckets, conditional skip via ``lax.cond``, and the
dynamic-loss-scale update — compiles into ONE XLA executable per optimizer:

* keyed on (pytree structure, leaf shapes/dtypes, static config) — the same
  things ``jax.jit`` retraces on, so cache misses == XLA compiles and
  ``stats()`` makes retrace regressions observable;
* ``donate_argnums`` on params, optimizer state and scaler state — XLA
  writes the new params/moments into the old buffers (``tf.aliasing_output``
  in the lowered HLO), so steady-state optimizer stepping allocates nothing.
  Donation follows the "auto" policy: on for tpu/gpu, off for cpu (XLA cpu
  accepts donate_argnums but degrades it to defensive copies — measured 2×
  step time; see :func:`set_donation`).  Consequence when on: any reference
  to a PRE-step ``p.data`` (or moment array) a caller stashed is
  invalidated by the step — copy first if you need it;
* all scalar hyperparameters (lr, betas, eps, weight_decay, step) enter as
  traced device scalars, so lr/wd/beta schedules never recompile.

The stateful optimizers (``apex_tpu.optimizers``, ``contrib.optimizers``)
collect their ``param_groups`` into pure pytrees and dispatch here; the amp
hooks (``_process_optimizer``, ``handle.scale_loss``) route the unscale /
master→model copy / deferred scale update through the same cache.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax import lax

from ..observe import registry as _obs
from ..observe import spans as _spans

_f32 = jnp.float32

#: opt-in ``span("dispatch")`` around every eager step-cache dispatch.
#: Off by default: the eager optimizer hot path is microbenchmarked
#: (``bench.py --opt-microbench``) and a per-step span event would be a
#: measurable fraction of a small fused step; the dispatch *counters*
#: always flow through the observe registry regardless.
_DISPATCH_SPANS = False


def set_dispatch_spans(enable: bool) -> None:
    """Enable/disable ``span("dispatch")`` around eager cache dispatches."""
    global _DISPATCH_SPANS
    _DISPATCH_SPANS = bool(enable)


def _leaf_sig(leaf):
    return (tuple(leaf.shape), jnp.dtype(leaf.dtype).name)


def signature(tree):
    """Hashable (treedef, leaf shapes/dtypes) key for an argument pytree —
    exactly what jit retraces on (all leaves enter strongly typed)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, tuple(_leaf_sig(l) for l in leaves))


def _example_avals(tree):
    return jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(tuple(l.shape), jnp.dtype(l.dtype)),
        tree)


class StepCache:
    """Compiled step-program cache with compile/dispatch counters.

    One entry per (kind, static config, argument signature); entries hold
    the jitted callable plus a ShapeDtypeStruct example tree so callers
    (tests, tooling) can re-lower a cached program without live arrays.
    LRU-capped so dead parameter sets cannot pin executables forever.
    """

    _TOP_COUNTERS = ("compiles", "cache_hits", "dispatches",
                     "multi_tensor_calls")
    _KIND_COUNTERS = ("compiles", "cache_hits", "dispatches")

    def __init__(self, cap: int = 128, metrics_prefix: str = "step_cache."):
        self._cap = cap
        self._prefix = metrics_prefix
        self._registry = _obs.get_registry()
        self._lock = threading.RLock()
        self._programs: OrderedDict = OrderedDict()
        self.reset_stats()

    # -- stats -------------------------------------------------------------
    # Counters live in the apex_tpu.observe registry (names
    # ``step_cache.<counter>`` / ``step_cache.kind.<kind>.<counter>``);
    # ``stats()`` reconstructs the historical dict shape from them so the
    # public surface — and every test pinned to it — is unchanged.

    def reset_stats(self):
        self._registry.remove(self._prefix)

    def _bump(self, name, kind=None):
        self._registry.counter(self._prefix + name).inc()
        if kind is not None:
            self._registry.counter(
                f"{self._prefix}kind.{kind}.{name}").inc()

    def stats(self) -> dict:
        """Counters for regression tracking.

        ``compiles`` is the analogue of the reference's kernel-*build* cost
        (one per new program shape), ``dispatches`` of its per-step kernel
        *launch* count — except one dispatch here covers what the CUDA
        reference spreads over dozens of ``multi_tensor_*`` launches.
        ``multi_tensor_calls`` counts eager multi-tensor op invocations for
        a direct launch-count comparison with the reference.
        """
        counters = self._registry.snapshot()["counters"]
        out = {n: counters.get(self._prefix + n, 0)
               for n in self._TOP_COUNTERS}
        by_kind: dict = {}
        kind_prefix = self._prefix + "kind."
        for full, value in counters.items():
            if not full.startswith(kind_prefix):
                continue
            kind, _, cname = full[len(kind_prefix):].rpartition(".")
            if kind and cname in self._KIND_COUNTERS:
                by_kind.setdefault(
                    kind, {n: 0 for n in self._KIND_COUNTERS})[cname] = value
        with self._lock:
            out["programs"] = len(self._programs)
        out["by_kind"] = by_kind
        return out

    # -- cache -------------------------------------------------------------
    def program(self, kind: str, static_key, args, build):
        """Return the compiled program for ``args``, building on a miss.

        ``static_key`` must be hashable and capture every Python-level value
        the built program closes over; ``args`` is the exact argument tuple
        the program will be called with (its structure + shapes/dtypes
        complete the key).
        """
        key = (kind, static_key, signature(args))
        with self._lock:
            entry = self._programs.pop(key, None)
            if entry is not None:
                self._programs[key] = entry     # pop + reinsert = LRU
                self._bump("cache_hits", kind)
                return entry["fn"]
        fn = build()
        with self._lock:
            while len(self._programs) >= self._cap:
                self._programs.popitem(last=False)
            self._programs[key] = {"kind": kind, "fn": fn,
                                   "example": _example_avals(args)}
            self._bump("compiles", kind)
        return fn

    def entries(self):
        """Snapshot of cached programs: [{kind, fn, example}] — ``example``
        is a ShapeDtypeStruct tree accepted by ``fn.lower(*example)``."""
        with self._lock:
            return [dict(e) for e in self._programs.values()]

    def clear(self):
        with self._lock:
            self._programs.clear()


#: process-global cache shared by every optimizer / amp hook
step_cache = StepCache()

#: buffer-donation policy: "auto" donates on backends with real input→output
#: buffer aliasing (tpu/gpu) and skips donation on cpu, where XLA accepts
#: donate_argnums but degrades it to defensive copies (measured 2× eager
#: FusedAdam step time at 10M params).  Tests force True to inspect the
#: aliasing in lowered HLO; the flag is part of every program cache key.
_DONATE = "auto"


def set_donation(mode):
    """Set the donation policy: True, False, or "auto" (default)."""
    global _DONATE
    if mode not in (True, False, "auto"):
        raise ValueError(f"donation mode must be True/False/'auto', "
                         f"got {mode!r}")
    _DONATE = mode


def donation_enabled() -> bool:
    if _DONATE == "auto":
        return jax.default_backend() not in ("cpu",)
    return bool(_DONATE)


def stats() -> dict:
    return step_cache.stats()


def reset_stats():
    step_cache.reset_stats()


def clear():
    step_cache.clear()


def record_multi_tensor_call():
    step_cache._bump("multi_tensor_calls")


def static_plan_key(plan):
    """Normalize a ``parallel.auto.Plan`` (or None) into the hashable
    tuple program keys embed — ``(dp, tp, sp, zero_stage, accum,
    chunked_loss)``.  Threading the plan through the STATIC key keeps
    compiled executables per-plan observables: two plans that would
    otherwise collide on signature (same shapes, different mesh
    factorization driven by the wrapper) never share a program entry,
    and ``stats()['by_kind']`` stays meaningful under ``parallel=``.
    None (an unplanned step) passes through as None."""
    if plan is None:
        return None
    return tuple(plan.key())


def _dispatch(fn, args, kind):
    """Count (and, when enabled, span-wrap) one program dispatch."""
    step_cache._bump("dispatches", kind)
    if _DISPATCH_SPANS:
        with _spans.span("dispatch", kind=kind):
            return fn(*args)
    return fn(*args)


# ---------------------------------------------------------------------------
# Whole-optimizer step programs
# ---------------------------------------------------------------------------
#
# ``update(static_cfg, donated, grads, hyper, flag) -> new_donated`` is a
# module-level pure function supplied by each optimizer; ``donated`` holds
# params + optimizer state (+ fp16 model copies under amp O2), ``grads`` the
# consumed gradients, ``hyper`` the traced scalar hyperparameters.  The
# whole update sits inside ``lax.cond`` on the overflow flag, so a flagged
# step leaves every buffer untouched without leaving the executable.


def optimizer_step(kind: str, static_cfg, update, flag, donated, grads,
                   hyper):
    """Dispatch one optimizer step as a single cached XLA executable.

    Donates ``donated`` (params + optimizer state): the caller must rebind
    every returned leaf and drop references to the inputs.

    No ``lax.cond`` here: on this path the overflow flag is reference-exact
    semantics — the Adam/LAMB/NovoGrad kernels deliberately ignore it
    (multi_tensor_adam.cu:40-41) and the SGD op gates on it internally —
    and an XLA conditional would copy the whole donated tree at the branch
    boundary every step.  The fused amp path
    (:func:`optimizer_step_with_scaler`), where a skip can actually occur,
    is the one that wraps the update in ``lax.cond``.
    """

    donate = donation_enabled()

    def build():
        def run(flag, donated, grads, hyper):
            return update(static_cfg, donated, grads, hyper, flag)
        return jax.jit(run, donate_argnums=(1,) if donate else ())

    args = (flag, donated, grads, hyper)
    fn = step_cache.program(kind, (static_cfg, donate), args, build)
    return _dispatch(fn, args, kind)


def optimizer_step_with_scaler(kind: str, static_cfg, update, scaler_state,
                               scaler_cfg, donated, grads, hyper):
    """The fully-fused amp step: overflow-conditional optimizer update AND
    dynamic-loss-scale update in one executable, with the scaler state
    donated alongside params/optimizer state.  Zero host round-trips: the
    skip decision is ``lax.cond`` on the scaler's on-device overflow flag.

    ``scaler_cfg``: hashable kwargs tuple for
    :func:`apex_tpu.amp.scaler.update_scale_state`.
    Returns ``(new_scaler_state, new_donated)``.
    """
    from ..amp.scaler import update_scale_state

    donate = donation_enabled()

    def build():
        kw = dict(scaler_cfg)

        def run(sstate, donated, grads, hyper):
            flag = sstate.overflow
            new_d = lax.cond(
                flag > 0, lambda d: d,
                lambda d: update(static_cfg, d, grads, hyper,
                                 jnp.zeros((), jnp.int32)), donated)
            new_s, _ = update_scale_state(sstate, **kw)
            return new_s, new_d
        return jax.jit(run, donate_argnums=(0, 1) if donate else ())

    args = (scaler_state, donated, grads, hyper)
    fn = step_cache.program(kind, (static_cfg, scaler_cfg, donate), args,
                            build)
    return _dispatch(fn, args, kind)


# ---------------------------------------------------------------------------
# amp programs: unscale / grad-accumulate / master→model copy
# ---------------------------------------------------------------------------


def unscale(flag, model_grads, out_dtypes, inv_scale,
            check_overflow: bool = True):
    """Whole-step grad unscale + overflow check as one executable
    (``master = model_grad * inv_scale``, flag set on non-finite inputs).
    Returns ``(new_flag, master_grads)``.
    """
    out_names = tuple(jnp.dtype(d).name for d in out_dtypes)
    grads = list(model_grads)

    def build():
        from .. import ops

        def run(flag, grads, inv):
            outs = [jnp.zeros(g.shape, d) for g, d in zip(grads, out_names)]
            new_flag, new = ops.multi_tensor_scale(
                flag, [list(grads), outs], inv)
            return (new_flag if check_overflow else flag), new
        return jax.jit(run)

    args = (flag, grads, jnp.asarray(inv_scale, _f32))
    fn = step_cache.program("amp_unscale", (out_names, bool(check_overflow)),
                            args, build)
    return _dispatch(fn, args, "amp_unscale")


def unscale_with_stashed(flag, model_grads, stashed_grads, a, b):
    """Fused ``out = a*model + b*stashed`` accumulation (one executable),
    flagging non-finite model grads.  Returns ``(new_flag, master_grads)``.
    """
    model = list(model_grads)
    stashed = list(stashed_grads)

    def build():
        from .. import ops

        def run(flag, model, stashed, a, b):
            outs = [jnp.zeros(s.shape, s.dtype) for s in stashed]
            return ops.multi_tensor_axpby(
                flag, [list(model), list(stashed), outs], a, b, 0)
        return jax.jit(run)

    args = (flag, model, stashed, jnp.asarray(a, _f32), jnp.asarray(b, _f32))
    fn = step_cache.program("amp_axpby", (), args, build)
    return _dispatch(fn, args, "amp_axpby")


def master_to_model(masters, model_vals):
    """fp32 master → half model copy as one executable, donating the stale
    model buffers (each output aliases the old copy it replaces)."""

    donate = donation_enabled()

    def build():
        def run(masters, old):
            return [m.astype(o.dtype) for m, o in zip(masters, old)]
        return jax.jit(run, donate_argnums=(1,) if donate else ())

    args = (list(masters), list(model_vals))
    fn = step_cache.program("amp_master_to_model", (donate,), args, build)
    return _dispatch(fn, args, "amp_master_to_model")
