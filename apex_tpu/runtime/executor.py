"""One-runtime executor: the single dispatch choke point.

PR 1 (the eager optimizer surface) and PR 3 (the fused train step) each
grew their own route into the step-program cache — duplicated donation
policy, dispatch counting, span/heartbeat plumbing, and carry handling.
This module collapses both onto one :class:`Executor`: every compiled
step program in the library — the four ``optimizers/fused_*`` +
``contrib/optimizers`` eager routes, the amp unscale / axpby /
master→model programs, the fused ``train_step``, the GSPMD
``zero_train_step``, and the planner's shard_map dispatch — is described
by a :class:`Program` and submitted here.  The executor owns:

* **compilation** — ``jax.jit`` is called in exactly one place
  (:meth:`Executor._jit`); programs are cached through
  :class:`~apex_tpu.runtime.step_cache.StepCache`, so ``stats()`` keeps
  pinning 1 compile + 1 dispatch per window on every surface (the
  EXEC-BYPASS lint rule enforces that no other module dispatches);
* **donation policy** — :class:`DonationPolicy` is the one place the
  True/False/"auto" buffer-donation decision lives (the copies that
  used to sit in step_cache, training/step.py and the amp handle are
  delegates now);
* **observability** — dispatch spans and stall-watchdog heartbeats are
  emitted here, uniformly for the fused and eager kinds;
* **overlap scheduling** — the knobs for ZeRO all-gather prefetch
  (:func:`overlap_enabled`, consumed by the fused step's scanned
  window) and async H2D double-buffering (:meth:`Executor.drive`,
  fused with :class:`~apex_tpu.runtime.data.DataPrefetcher`).

See ``docs/executor.md`` for the contract and the migration table from
the old per-surface ``step_cache`` call sites.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..observe import registry as _obs
from ..observe import spans as _spans
from ..observe import telemetry as _obs_telemetry
from ..observe import watchdog as _obs_watchdog
from . import step_cache as _sc

_f32 = jnp.float32

#: program kinds that are whole-training-window dispatches: these always
#: get a ``span("dispatch")`` and a watchdog heartbeat.  Eager kinds
#: (optimizer/amp programs) span only under
#: ``step_cache.set_dispatch_spans(True)`` — the eager hot path is
#: microbenchmarked and a per-step span event is a measurable fraction
#: of a small fused step — and never heartbeat (many eager dispatches
#: compose into one logical step; the *step* is the liveness unit).
TRAIN_KINDS = frozenset({"train_step", "zero_train_step",
                         "gan_train_step"})

#: serving-loop kinds (apex_tpu.serve): like train kinds they are the
#: unit of forward progress — every tick spans and heartbeats, so the
#: stall watchdog guards the decode loop the same way it guards the
#: train loop.  Unlike eager kinds there is no microbenchmarked
#: hot path concern: a serve dispatch covers a whole batched tick.
SERVE_KINDS = frozenset({"prefill_step", "decode_step",
                         "draft_prefill_step", "spec_verify_step"})

#: rollout-loop kinds (apex_tpu.rollout): the generate-then-train
#: runtime's own dispatches.  ``weight_publish`` is the one fused
#: train→serve cast (masters cast once to the serve dtype in a single
#: dispatch); like train/serve kinds it spans and heartbeats — a wedged
#: publish stalls the whole loop, so the watchdog must see it.
ROLLOUT_KINDS = frozenset({"weight_publish"})

_UNSET = object()


class DonationPolicy:
    """The one buffer-donation decision (satellite of the one-runtime
    refactor: this policy used to be re-derived in step_cache,
    training/step.py and the amp handle).

    ``"auto"`` donates on backends with real input→output buffer
    aliasing (tpu/gpu) and skips donation on cpu, where XLA accepts
    ``donate_argnums`` but degrades it to defensive copies (measured 2×
    eager FusedAdam step time at 10M params — and jax 0.4.x's
    persistently-cached CPU executables resolve the aliasing of
    deserialized donated programs incorrectly, returning stale
    outputs).  The resolved flag is part of every program cache key.
    """

    def __init__(self, mode="auto"):
        self._mode = mode

    @property
    def mode(self):
        return self._mode

    def set(self, mode) -> None:
        if mode not in (True, False, "auto"):
            raise ValueError(f"donation mode must be True/False/'auto', "
                             f"got {mode!r}")
        self._mode = mode

    @property
    def enabled(self) -> bool:
        """The policy resolved against the current default backend."""
        return self.resolve(self._mode)

    def resolve(self, request) -> bool:
        """Resolve a per-call request (True/False/"auto") to a bool;
        "auto" defers to the process-wide policy."""
        if request == "auto":
            if self._mode == "auto":
                return jax.default_backend() not in ("cpu",)
            request = self._mode
        return bool(request)


#: process-global donation policy (``step_cache.set_donation`` /
#: ``donation_enabled`` are thin delegates onto this object)
donation = DonationPolicy()


# ---------------------------------------------------------------------------
# Overlap policy: ZeRO all-gather prefetch + async H2D double-buffering
# ---------------------------------------------------------------------------

#: True/False/"auto" per overlap dimension.  "auto" enables overlap on
#: backends with async collectives / transfers worth hiding (tpu/gpu)
#: and disables it on cpu, where XLA:CPU runs collectives synchronously
#: — the schedule transformation is semantically a no-op there (the
#: bitwise-parity tests force it on to prove exactly that).
_OVERLAP = {"gather": "auto", "h2d": "auto"}


def set_overlap(gather=None, h2d=None) -> None:
    """Set the executor overlap knobs; each accepts True/False/"auto"
    (None leaves the knob unchanged)."""
    for name, mode in (("gather", gather), ("h2d", h2d)):
        if mode is None:
            continue
        if mode not in (True, False, "auto"):
            raise ValueError(f"overlap {name} mode must be "
                             f"True/False/'auto', got {mode!r}")
        _OVERLAP[name] = mode


def overlap_enabled(which: str, override=None) -> bool:
    """Resolve an overlap knob ("gather" or "h2d") to a bool; a
    per-step ``override`` of True/False wins, None/"auto" defers to the
    process-wide knob."""
    mode = _OVERLAP[which] if override in (None, "auto") else override
    if mode == "auto":
        return jax.default_backend() not in ("cpu",)
    return bool(mode)


# ---------------------------------------------------------------------------
# Measured H2D bandwidth: an EWMA over real device_put transfers
# ---------------------------------------------------------------------------

#: {"bw": bytes/s EWMA or None, "n": samples}.  The data path
#: (runtime.data) feeds it from timed device_put calls; the planner's
#: offload term prices host traffic against it, falling back to the
#: ChipSpec.h2d_bw prior until a real transfer has been observed.
_H2D_EWMA = {"bw": None, "n": 0}

#: ignore sub-64KiB transfers — latency-dominated, not bandwidth
_H2D_MIN_BYTES = 1 << 16


def note_h2d(nbytes: int, seconds: float) -> None:
    """Record one host-to-device transfer (bytes, wall seconds) into
    the bandwidth EWMA.  Tiny or instant transfers are ignored."""
    if nbytes < _H2D_MIN_BYTES or seconds <= 0:
        return
    bw = nbytes / seconds
    prev = _H2D_EWMA["bw"]
    _H2D_EWMA["bw"] = bw if prev is None else 0.8 * prev + 0.2 * bw
    _H2D_EWMA["n"] += 1
    _obs.gauge("executor.h2d_bw").set(_H2D_EWMA["bw"])


def measured_h2d_bw() -> Optional[float]:
    """The measured H2D bandwidth (bytes/s EWMA) or None before any
    real transfer has been timed."""
    return _H2D_EWMA["bw"]


def reset_h2d_bw() -> None:
    """Forget measured H2D bandwidth (tests)."""
    _H2D_EWMA["bw"] = None
    _H2D_EWMA["n"] = 0


#: the cluster membership epoch this process last agreed to (None
#: outside a cluster run).  Dispatch spans carry it so a trace mixing
#: pre- and post-reshard steps attributes each dispatch to the
#: membership view it ran under (apex_tpu.cluster sets it on recover).
_CLUSTER_EPOCH: Optional[int] = None


def set_cluster_epoch(epoch: Optional[int]) -> None:
    """Tag subsequent dispatch spans with the cluster membership epoch
    (None clears the tag)."""
    global _CLUSTER_EPOCH
    _CLUSTER_EPOCH = None if epoch is None else int(epoch)


def cluster_epoch() -> Optional[int]:
    """The membership epoch dispatches are currently tagged with."""
    return _CLUSTER_EPOCH


# ---------------------------------------------------------------------------
# Program descriptor
# ---------------------------------------------------------------------------


class Program:
    """Everything the executor needs to compile and dispatch one step
    program: the raw Python function plus its jit options.  Call sites
    never call ``jax.jit`` themselves (EXEC-BYPASS) — they describe the
    program and :meth:`Executor.submit` it.

    ``static_key`` must be hashable and capture every Python-level value
    ``fn`` closes over (the argument signature completes the cache key);
    ``wrap`` is an optional transform applied before jit (the planner's
    shard_map); ``in_shardings``/``out_shardings`` are forwarded to
    ``jax.jit`` only when given (the GSPMD ZeRO window).
    """

    __slots__ = ("kind", "static_key", "fn", "donate_argnums",
                 "in_shardings", "out_shardings", "wrap", "_jitted")

    def __init__(self, kind: str, static_key, fn: Callable, *,
                 donate_argnums: Tuple[int, ...] = (),
                 in_shardings=_UNSET, out_shardings=_UNSET,
                 wrap: Optional[Callable] = None):
        self.kind = kind
        self.static_key = static_key
        self.fn = fn
        self.donate_argnums = tuple(donate_argnums)
        self.in_shardings = in_shardings
        self.out_shardings = out_shardings
        self.wrap = wrap
        self._jitted = None


class Executor:
    """The dispatch choke point.  Stateless beyond its cache handle —
    the process-global :data:`executor` is the one instance the library
    routes through."""

    def __init__(self, cache: Optional[_sc.StepCache] = None):
        self._cache = cache if cache is not None else _sc.step_cache

    @property
    def cache(self) -> _sc.StepCache:
        return self._cache

    def stats(self) -> dict:
        """Compile/dispatch counters (the step cache's, unchanged)."""
        return self._cache.stats()

    # -- compilation -------------------------------------------------------

    def _jit(self, program: Program):
        """The ONE ``jax.jit`` call of the library's step dispatch.
        Memoized per Program instance so the diagnostic surface
        (:meth:`jit`) and the cached dispatch path share a single jitted
        callable."""
        if program._jitted is None:
            fn = program.fn if program.wrap is None else program.wrap(
                program.fn)
            kw: dict = {}
            if program.in_shardings is not _UNSET:
                kw["in_shardings"] = program.in_shardings
            if program.out_shardings is not _UNSET:
                kw["out_shardings"] = program.out_shardings
            program._jitted = jax.jit(
                fn, donate_argnums=program.donate_argnums, **kw)
        return program._jitted

    def jit(self, program: Program):
        """Build (without caching or counting) the jitted callable for a
        Program — the diagnostic surface: tests ``.lower()`` the result
        to inspect shardings / aliasing without dispatching."""
        return self._jit(program)

    def compile(self, program: Program, args):
        """Resolve ``program`` for ``args`` through the step cache
        (compile on miss, LRU hit otherwise) without dispatching."""
        return self._cache.program(program.kind, program.static_key, args,
                                   lambda: self._jit(program))

    # -- dispatch ----------------------------------------------------------

    def submit(self, program: Program, args, *, step: Optional[int] = None):
        """Compile-or-hit, count, span, heartbeat, dispatch.

        ``step``: the caller's 1-based step count for the watchdog
        heartbeat (train and serve kinds; dispatch returning means the
        host made forward progress — execution is async, a wedged
        backend blocks the dispatch itself).  Eager kinds pass None:
        they span only under ``step_cache.set_dispatch_spans(True)``
        and never heartbeat.
        """
        fn = self.compile(program, args)
        self._cache._bump("dispatches", program.kind)
        beat = (program.kind in TRAIN_KINDS or program.kind in SERVE_KINDS
                or program.kind in ROLLOUT_KINDS)
        if beat or _sc._DISPATCH_SPANS:
            tags = {"kind": program.kind}
            if _CLUSTER_EPOCH is not None:
                tags["cluster_epoch"] = _CLUSTER_EPOCH
            with _spans.span("dispatch", **tags):
                out = fn(*args)
        else:
            out = fn(*args)
        if beat and step is not None:
            _obs_watchdog.heartbeat(step=step)
        return out

    # -- async H2D double-buffering ---------------------------------------

    def drive(self, step, loader, *, max_steps: Optional[int] = None,
              **prefetch_kwargs):
        """Run a train step over a loader with the next window's H2D
        transfer overlapped under the current window's dispatch.

        ``loader`` is either a :class:`~apex_tpu.runtime.data.
        DataPrefetcher` (used as-is) or any host batch iterable, wrapped
        in one (``prefetch_kwargs`` forwarded — pass ``accum_steps=K``
        for stacked accumulation windows).  The prefetcher's bounded
        depth-2 queue is the executor's two-deep device-side input
        buffer: its worker thread issues exactly one ``span("h2d")``
        transfer per window, and because step dispatch is async the
        transfer for window N+1 is in flight while window N computes.
        Respecting the ``h2d`` overlap knob, ``overlap_enabled("h2d")
        is False`` degrades to a single-buffered (depth-1) queue —
        transfer and compute serialize, which is the overlap-off arm
        the microbenchmark measures.  Returns the list of per-window
        losses.
        """
        from .data import DataPrefetcher

        own = not isinstance(loader, DataPrefetcher)
        if own:
            prefetch_kwargs.setdefault(
                "depth", 2 if overlap_enabled("h2d") else 1)
            loader = DataPrefetcher(loader, **prefetch_kwargs)
        losses = []
        try:
            for batch in loader:
                losses.append(step(*batch))
                if max_steps is not None and len(losses) >= max_steps:
                    break
        finally:
            if own:
                loader.close()
        return losses


#: process-global executor shared by every surface
executor = Executor()


def drain_telemetry(step) -> Optional[dict]:
    """Host-sync a step's on-device telemetry accumulator and reset it.

    The shared carry-drain for every step kind (fused ``TrainStep``,
    GSPMD ``ZeroTrainStep``, planned shard_map steps): the ONE
    deliberate host round-trip of the telemetry path, in eager code
    outside jit, so the compiled window program stays 1 compile +
    1 dispatch.  Emits a ``train.telemetry`` event + gauges and returns
    the record (None when telemetry is off or no window completed since
    the last drain).  ``step`` needs ``.state`` (a StepState) and
    ``.calls``.
    """
    telem = step.state.telem
    if telem is None:
        return None
    host = jax.device_get(telem)
    windows = int(host.windows)
    if windows == 0:
        return None
    rec = _obs.event(
        "train.telemetry",
        step=step.calls,
        windows=windows,
        loss_mean=float(host.loss_sum) / windows,
        grad_norm=float(host.grad_norm),
        loss_scale=float(host.loss_scale),
        overflow_count=int(host.overflow_count))
    _obs.gauge("train.loss").set(rec["loss_mean"])
    _obs.gauge("train.grad_norm").set(rec["grad_norm"])
    _obs.gauge("train.loss_scale").set(rec["loss_scale"])
    _obs.counter("train.overflow_windows").inc(rec["overflow_count"])
    step.state = step.state._replace(telem=_obs_telemetry.init_telemetry())
    return rec


# ---------------------------------------------------------------------------
# Whole-optimizer step programs (the eager surface, migrated here from
# step_cache — PR 1's routes now submit Program descriptors like
# everything else)
# ---------------------------------------------------------------------------
#
# ``update(static_cfg, donated, grads, hyper, flag) -> new_donated`` is a
# module-level pure function supplied by each optimizer; ``donated`` holds
# params + optimizer state (+ fp16 model copies under amp O2), ``grads`` the
# consumed gradients, ``hyper`` the traced scalar hyperparameters.


def optimizer_step(kind: str, static_cfg, update, flag, donated, grads,
                   hyper):
    """Dispatch one optimizer step as a single cached XLA executable.

    Donates ``donated`` (params + optimizer state): the caller must rebind
    every returned leaf and drop references to the inputs.

    No ``lax.cond`` here: on this path the overflow flag is reference-exact
    semantics — the Adam/LAMB/NovoGrad kernels deliberately ignore it
    (multi_tensor_adam.cu:40-41) and the SGD op gates on it internally —
    and an XLA conditional would copy the whole donated tree at the branch
    boundary every step.  The fused amp path
    (:func:`optimizer_step_with_scaler`), where a skip can actually occur,
    is the one that wraps the update in ``lax.cond``.
    """
    donate = donation.enabled

    def run(flag, donated, grads, hyper):
        return update(static_cfg, donated, grads, hyper, flag)

    prog = Program(kind, (static_cfg, donate), run,
                   donate_argnums=(1,) if donate else ())
    return executor.submit(prog, (flag, donated, grads, hyper))


def optimizer_step_with_scaler(kind: str, static_cfg, update, scaler_state,
                               scaler_cfg, donated, grads, hyper):
    """The fully-fused amp step: overflow-conditional optimizer update AND
    dynamic-loss-scale update in one executable, with the scaler state
    donated alongside params/optimizer state.  Zero host round-trips: the
    skip decision is ``lax.cond`` on the scaler's on-device overflow flag.

    ``scaler_cfg``: hashable kwargs tuple for
    :func:`apex_tpu.amp.scaler.update_scale_state`.
    Returns ``(new_scaler_state, new_donated)``.
    """
    from ..amp.scaler import update_scale_state

    donate = donation.enabled
    kw = dict(scaler_cfg)

    def run(sstate, donated, grads, hyper):
        flag = sstate.overflow
        new_d = lax.cond(
            flag > 0, lambda d: d,
            lambda d: update(static_cfg, d, grads, hyper,
                             jnp.zeros((), jnp.int32)), donated)
        new_s, _ = update_scale_state(sstate, **kw)
        return new_s, new_d

    prog = Program(kind, (static_cfg, scaler_cfg, donate), run,
                   donate_argnums=(0, 1) if donate else ())
    return executor.submit(prog, (scaler_state, donated, grads, hyper))


# ---------------------------------------------------------------------------
# amp programs: unscale / grad-accumulate / master→model copy
# ---------------------------------------------------------------------------


def unscale(flag, model_grads, out_dtypes, inv_scale,
            check_overflow: bool = True):
    """Whole-step grad unscale + overflow check as one executable
    (``master = model_grad * inv_scale``, flag set on non-finite inputs).
    Returns ``(new_flag, master_grads)``.
    """
    out_names = tuple(jnp.dtype(d).name for d in out_dtypes)

    def run(flag, grads, inv):
        from .. import ops
        outs = [jnp.zeros(g.shape, d) for g, d in zip(grads, out_names)]
        new_flag, new = ops.multi_tensor_scale(
            flag, [list(grads), outs], inv)
        return (new_flag if check_overflow else flag), new

    prog = Program("amp_unscale", (out_names, bool(check_overflow)), run)
    return executor.submit(
        prog, (flag, list(model_grads), jnp.asarray(inv_scale, _f32)))


def unscale_with_stashed(flag, model_grads, stashed_grads, a, b):
    """Fused ``out = a*model + b*stashed`` accumulation (one executable),
    flagging non-finite model grads.  Returns ``(new_flag, master_grads)``.
    """

    def run(flag, model, stashed, a, b):
        from .. import ops
        outs = [jnp.zeros(s.shape, s.dtype) for s in stashed]
        return ops.multi_tensor_axpby(
            flag, [list(model), list(stashed), outs], a, b, 0)

    prog = Program("amp_axpby", (), run)
    return executor.submit(
        prog, (flag, list(model_grads), list(stashed_grads),
               jnp.asarray(a, _f32), jnp.asarray(b, _f32)))


def master_to_model(masters, model_vals):
    """fp32 master → half model copy as one executable, donating the stale
    model buffers (each output aliases the old copy it replaces)."""
    donate = donation.enabled

    def run(masters, old):
        return [m.astype(o.dtype) for m, o in zip(masters, old)]

    prog = Program("amp_master_to_model", (donate,), run,
                   donate_argnums=(1,) if donate else ())
    return executor.submit(prog, (list(masters), list(model_vals)))
