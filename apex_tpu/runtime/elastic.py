"""Elastic training: preemption-driven re-planning with cross-plan
checkpoint resharding.

The resilience runtime survives a kill and resumes — onto the SAME
topology.  On preemptible TPU capacity the dominant real-world failure
is the pod slice coming back smaller (8 chips → 4): the old plan no
longer fits the device set, and the checkpoint's layout no longer
matches any step that device set can build.  Poplar (arXiv:2408.12596)
and AMP (arXiv:2210.07297) make the case that the planner must be
elasticity-aware — on a device-set change, re-plan and *reshard*
persisted state into the new layout rather than abort.  This module
composes the two subsystems the repo already owns —
``runtime.resilience`` and ``parallel.auto`` — into that recovery loop
(ROADMAP item 3):

1. detect the CURRENT device set (:func:`current_devices`; the
   ``device.loss`` chaos hook lets tier-1 tests shrink/regrow the
   8-virtual-CPU-device mesh deterministically);
2. re-plan for it (``parallel.auto.plan_training`` — the same
   analytical cost model behind ``parallel="auto"``);
3. rebuild the step through ``make_train_step(parallel=plan)`` — the
   rebuilt step re-submits through ``runtime.executor`` under a new
   ``static_plan_key``, so the executor's cache distinguishes the new
   plan from the old one's programs (both stay warm across regrows);
4. reshard the newest valid checkpoint into the new layout
   (:meth:`~apex_tpu.runtime.resilience.CheckpointManager.
   restore_resharded` — fp32 masters bit-exact) and resume.

Usage — the whole point is that the SAME script, rerun after a
preemption, recovers onto whatever came back::

    trainer = ElasticTrainer("ckpts/", model, opt, loss_fn,
                             example_batch=(x, y))
    start = trainer.restore()          # detect → plan → build → reshard
    for i, (x, y) in enumerate(loader, start=start):
        loss = trainer(x, y)
        if i % 1000 == 0:
            trainer.save(i)

Each :meth:`~ElasticTrainer.restore` emits one ``elastic.restore``
event (plus ``elastic.replan``/``elastic.reshard`` spans) into the
``apex_tpu.observe`` registry — the quantities ``bench.py --elastic``
publishes.  ``trainer.telemetry`` keeps the same fields as a plain dict
alias for one release.
"""
from __future__ import annotations

import time
import warnings
from typing import Callable, Optional

from ..observe import registry as _obs
from ..observe import spans as _spans
from . import chaos as _chaos
from .resilience import CheckpointCorruptError, CheckpointManager


def current_devices(devices=None) -> list:
    """The device set the elastic layer plans for: ``jax.devices()`` (or
    the caller's explicit subset) filtered through the ``device.loss``
    chaos hook.  A callable chaos action's return value replaces the
    set — an int ``k`` keeps the first ``k`` devices, a sequence becomes
    the set verbatim — so tier-1 tests simulate preempt→shrink→regrow
    deterministically without ever owning real preemptible capacity."""
    from ..parallel.auto import _resolve_devices
    devs = _resolve_devices(devices)
    if _chaos.active():
        res = _chaos.hook("device.loss", n=len(devs), devices=tuple(devs))
        if isinstance(res, int) and not isinstance(res, bool):
            if not 1 <= res <= len(devs):
                raise ValueError(
                    f"device.loss hook kept {res} of {len(devs)} devices")
            devs = devs[:res]
        elif isinstance(res, (list, tuple)):
            devs = list(res)
    return devs


class ElasticTrainer:
    """The restore→train→save loop that survives topology changes.

    Construction is cheap and does no planning; :meth:`restore` runs one
    full recovery cycle and must be called before training.  ``manager``
    may be a :class:`~apex_tpu.runtime.resilience.CheckpointManager` or
    a directory path.  ``example_batch`` feeds the planner (concrete
    arrays or ``ShapeDtypeStruct``\\ s — the GLOBAL batch; the plan
    shards it).  ``plan_filter``, when given, restricts the planner's
    ranked feasible plans (e.g. pin ``zero_stage`` so checkpoint-parity
    tests stay deterministic); the best surviving plan wins.
    ``plan_options`` passes through to ``plan_training`` (memory caps,
    ``accum_max``, ...), and remaining keyword arguments go to
    ``make_train_step`` (``half_dtype``, ``loss_scale``, ...)."""

    def __init__(self, manager, model, optimizer, loss_fn: Callable, *,
                 example_batch, plan_options: Optional[dict] = None,
                 plan_filter: Optional[Callable] = None, **step_kwargs):
        if isinstance(manager, str):
            manager = CheckpointManager(manager)
        self.manager = manager
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.example_batch = example_batch
        self.plan_options = dict(plan_options or {})
        self.plan_filter = plan_filter
        self.step_kwargs = dict(step_kwargs)
        self.step = None            # the live (planned) train step
        self.plan = None
        self.report = None
        self.devices = None
        self.resume_step = None     # checkpoint step restored, or None
        self.extras = {}
        self.telemetry = {}

    def restore(self, devices=None) -> int:
        """One elastic recovery cycle: detect devices → re-plan → build
        the step → reshard the newest valid checkpoint into it.  Returns
        the step number training continues FROM (0 on a fresh start,
        ``checkpoint_step + 1`` after a restore).  Corrupt checkpoints
        are scanned past with a warning (``restore_or_initialize``
        semantics); a structurally incompatible one raises
        :class:`~apex_tpu.runtime.resilience.CheckpointReshardError` —
        that is a config error, not damage, so no fallback."""
        from ..parallel import auto as _auto
        from ..training.step import make_train_step

        devs = current_devices(devices)
        t0 = time.perf_counter()
        with _spans.span("elastic.replan", n_devices=len(devs)):
            report = _auto.plan_training(
                self.model, self.optimizer, self.loss_fn,
                self.example_batch, devices=devs,
                half_dtype=self.step_kwargs.get("half_dtype"),
                keep_batchnorm_fp32=self.step_kwargs.get(
                    "keep_batchnorm_fp32", True),
                **self.plan_options)
            ranked = report.ranked if self.plan_filter is None else \
                [p for p in report.ranked if self.plan_filter(p)]
            if not ranked:
                raise RuntimeError(
                    f"elastic restore: no feasible plan for {len(devs)} "
                    f"device(s)"
                    + (" passed plan_filter" if self.plan_filter else "")
                    + "\n" + report.describe())
            plan = ranked[0]
            step = make_train_step(self.model, self.optimizer,
                                   self.loss_fn, parallel=plan,
                                   devices=devs, **self.step_kwargs)
            step.plan_report = report
        replan_ms = (time.perf_counter() - t0) * 1e3

        reshard_ms = 0.0
        resume = None
        extras = {}
        for s in reversed(self.manager.all_steps()):
            t1 = time.perf_counter()
            try:
                with _spans.span("elastic.reshard", ckpt_step=s):
                    resume, extras = self.manager.restore_resharded(
                        step, step=s)
                reshard_ms = (time.perf_counter() - t1) * 1e3
                break
            except CheckpointCorruptError as e:
                warnings.warn(
                    f"elastic restore: skipping corrupt checkpoint for "
                    f"step {s}: {e}", stacklevel=2)
            except FileNotFoundError:
                continue
        self.step, self.plan, self.report = step, plan, report
        self.devices = devs
        self.resume_step = resume
        self.extras = extras
        # one release of dict-alias compatibility; the registry event is
        # the durable surface (bench --elastic consumes it)
        self.telemetry = {
            "n_devices": len(devs),
            "plan": plan.name(),
            "plan_key": plan.key(),
            "replan_ms": round(replan_ms, 3),
            "reshard_ms": round(reshard_ms, 3),
            "resume_step": resume,
        }
        # per-leaf placement accounting from reshard_state's stats_out
        # (gathered restores; streamed restores report their own mode) —
        # "how much of the restore was zero-copy" is now in the event
        rstats = getattr(self.manager, "last_restore_stats", {}) or {}
        if rstats:
            self.telemetry["restore_mode"] = rstats.get("mode")
            for k in ("zero_copy_leaves", "copied_leaves",
                      "reshard_bytes_moved"):
                if k in rstats:
                    self.telemetry[k] = rstats[k]
        _obs.event("elastic.restore", **self.telemetry)
        _obs.histogram("elastic.replan_ms").observe(replan_ms)
        if resume is not None:
            _obs.histogram("elastic.reshard_ms").observe(reshard_ms)
        return 0 if resume is None else resume + 1

    def save(self, step_no: int, **extra) -> str:
        """Sharded atomic save through the one write path: the schema-3
        manifest records the live layout + plan, and the leaf shards
        stream to per-shard files (see docs/cluster.md)."""
        if self.step is None:
            raise RuntimeError("call restore() before save()")
        return self.manager.save_sharded(step_no, self.step, **extra)

    def __call__(self, *batch):
        if self.step is None:
            raise RuntimeError("call restore() before training")
        return self.step(*batch)


def elastic_restore(manager, model, optimizer, loss_fn: Callable, *,
                    example_batch, devices=None,
                    plan_options: Optional[dict] = None,
                    plan_filter: Optional[Callable] = None,
                    **step_kwargs) -> ElasticTrainer:
    """Functional entry point: build an :class:`ElasticTrainer` and run
    one :meth:`~ElasticTrainer.restore` cycle.  Returns the trainer —
    read ``.resume_step`` / ``.telemetry``, then call it to train."""
    trainer = ElasticTrainer(manager, model, optimizer, loss_fn,
                             example_batch=example_batch,
                             plan_options=plan_options,
                             plan_filter=plan_filter, **step_kwargs)
    trainer.restore(devices)
    return trainer
