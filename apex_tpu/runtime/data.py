"""Input pipeline: overlapped host→device prefetch.

TPU analogue of the reference examples' ``data_prefetcher``
(examples/imagenet/main_amp.py:264-313): there, a side CUDA stream overlaps
the H2D copy + normalize of batch N+1 with the compute of batch N.  Here the
same overlap comes from a background thread doing the host byte-work (native
normalize/cast, csrc/runtime.cpp) and issuing ``jax.device_put`` — JAX
transfers are async, and the jitted step's dispatch is too, so compute and
transfer pipeline naturally; the thread keeps the *host* work (decode,
normalize, layout) off the training loop's critical path.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np


class DataPrefetcher:
    """Wrap a batch iterable; yields device-resident (input, target) pairs
    one step ahead of consumption.

    ``loader`` yields (images, target) with images uint8 NHWC (the raw
    decode layout) or any float array.  uint8 NHWC input goes through the
    fused native normalize→NCHW path; ``half_dtype`` additionally casts to
    bf16/fp16 on host before transfer (halving H2D bytes).  Iteration
    protocol matches the reference: ``next()`` returns (None, None) at end.

    ``accum_steps=K`` delivers pre-stacked ``(K, B, ...)`` microbatch
    blocks for the fused accumulation step
    (``make_train_step(accum_steps=K, accum_stacked=True)``): K
    consecutive loader batches are normalized/cast individually, stacked
    on a new leading axis on the host, and transferred as one block — one
    ``device_put`` (and one step dispatch) per accumulation window instead
    of K.  The bounded queue keeps ``depth`` whole windows in flight, so
    block N+1's host byte-work and transfer overlap window N's compute
    exactly as with single batches.  A trailing partial window (loader
    exhausted mid-block) is dropped, like a ``drop_last`` loader — the
    step program's (K, B, ...) signature is static.

    ``depth`` is the double-buffering knob: ``Executor.drive`` picks 2
    (next window's transfer in flight under the current dispatch) or 1
    (serialized, the overlap-off arm) from the executor's ``h2d``
    overlap setting — see ``runtime/executor.py``.
    """

    def __init__(self, loader, mean=None, std=None, half_dtype=None,
                 device=None, depth: int = 2, threads: int = 0,
                 channels_last: bool = False, accum_steps: int = 1):
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self.accum_steps = accum_steps
        self.loader = iter(loader)
        # channels_last: keep uint8 batches NHWC through the normalize
        # (for nn.to_channels_last models — the decode layout IS the
        # compute layout, no transpose anywhere on the input path)
        self.channels_last = channels_last
        self.mean = np.asarray(
            mean if mean is not None else [0.485, 0.456, 0.406], np.float32)
        self.std = np.asarray(
            std if std is not None else [0.229, 0.224, 0.225], np.float32)
        self.half_dtype = half_dtype
        self.device = device
        self.threads = threads
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _prepare(self, images):
        from . import (f32_to_bf16, normalize_u8_nhwc_to_f32_nchw,
                       normalize_u8_nhwc_to_f32_nhwc)
        images = np.asarray(images)
        if images.dtype == np.uint8 and images.ndim == 4:
            norm = (normalize_u8_nhwc_to_f32_nhwc if self.channels_last
                    else normalize_u8_nhwc_to_f32_nchw)
            images = norm(images, self.mean, self.std, self.threads)
        if self.half_dtype is not None:
            import jax.numpy as jnp
            if jnp.dtype(self.half_dtype) == jnp.bfloat16 and \
                    images.dtype == np.float32:
                images = f32_to_bf16(images, self.threads)
            else:
                import ml_dtypes  # noqa: F401  (dtype registry)
                images = images.astype(jnp.dtype(self.half_dtype))
        return images

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer closed us (so an
        abandoned prefetcher never leaves the worker pinned on a full
        queue holding device buffers)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        import jax

        from ..observe import spans as _spans
        try:
            window = []
            for images, target in self.loader:
                if self._stop.is_set():
                    return
                from . import executor as _executor
                images = self._prepare(images)
                if self.accum_steps == 1:
                    target = np.asarray(target)
                    nbytes = (getattr(images, "nbytes", 0) +
                              getattr(target, "nbytes", 0))
                    t0 = time.perf_counter()
                    with _spans.span("h2d"):
                        images = jax.device_put(images, self.device)
                        target = jax.device_put(target, self.device)
                        jax.block_until_ready(target)
                    _executor.note_h2d(nbytes, time.perf_counter() - t0)
                    if not self._put((images, target)):
                        return
                    continue
                window.append((images, np.asarray(target)))
                if len(window) < self.accum_steps:
                    continue
                # host-side stack into the (K, B, ...) block the fused
                # accumulation step scans — one transfer per window
                block = np.stack([w[0] for w in window])
                tgt = np.stack([w[1] for w in window])
                window = []
                nbytes = block.nbytes + tgt.nbytes
                t0 = time.perf_counter()
                with _spans.span("h2d", accum_steps=self.accum_steps):
                    block = jax.device_put(block, self.device)
                    tgt = jax.device_put(tgt, self.device)
                    jax.block_until_ready(tgt)
                _executor.note_h2d(nbytes, time.perf_counter() - t0)
                if not self._put((block, tgt)):
                    return
            # a partial trailing window is dropped (drop_last semantics)
        except Exception as e:  # surface in the consumer thread
            self._put(e)
        self._put(None)

    def next(self):
        # exhausted stays exhausted: repeated next() keeps returning
        # (None, None) like the reference prefetcher, no deadlock
        if self._done:
            return None, None
        item = self._q.get()
        if item is None:
            self._done = True
            return None, None
        if isinstance(item, Exception):
            self._done = True
            raise item
        return item

    def close(self):
        """Release the worker and any queued device batches (safe to call
        any time, including after partial consumption)."""
        self._stop.set()
        self._done = True
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._worker.join(timeout=5)

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass

    def __iter__(self):
        while True:
            inp, tgt = self.next()
            if inp is None:
                return
            yield inp, tgt
