"""Resilience runtime: atomic/async checkpointing, preemption-safe
auto-resume, and escalation on overflow storms.

The reference documents a "bitwise accurate" save/resume workflow
(README.md:59-99 there) but its durability story ends at ``torch.save``:
a preemption mid-write corrupts the only copy, and nothing validates a
checkpoint before unpickling it.  On a TPU pod, preemption is routine —
this module makes the save/resume loop survive it:

* :func:`write_checkpoint_file` / :func:`read_checkpoint_file` — THE one
  checkpoint write path (the legacy ``apex_tpu.utils.save_checkpoint``
  delegates here).  Writes are atomic (tmp file + fsync + ``os.rename``);
  every file carries a manifest (schema version + per-component CRC32
  checksums) validated on load, raising the typed
  :class:`CheckpointCorruptError` instead of feeding garbage to
  ``load_state_dict``.  Pre-manifest pickles still load, with a warning.
* :class:`CheckpointManager` — rolling ``keep_n`` retention over a
  directory of step-numbered checkpoints, synchronous or async save
  (device→host transfer on the caller thread — one sync, exactly like the
  blocking path — then pickling + IO on a background thread behind a
  :class:`SaveHandle` that surfaces errors on ``wait()``), and
  :meth:`CheckpointManager.restore_or_initialize` auto-resume that scans
  newest→oldest past corrupt/partial checkpoints to the latest *valid*
  one.
* Elastic restore (schema 2): the manifest additionally records each
  component's sharding layout (per-leaf partition specs + mesh shape,
  captured BEFORE the host transfer gathers the shards) and the
  ``parallel.auto`` plan identity the state was saved under.
  :func:`reshard_state` / :meth:`CheckpointManager.restore_resharded`
  load a checkpoint saved under plan A into plan B's layout (fp32
  masters bit-exact), raising the typed :class:`CheckpointReshardError`
  naming the incompatible component when they can't;
  :mod:`apex_tpu.runtime.elastic` orchestrates the full
  detect→re-plan→reshard→resume cycle.
* Streaming shard IO (schema 3): :meth:`CheckpointManager.save_sharded`
  no longer gathers the state onto the host before pickling — each
  distinct array shard streams to its own file under
  ``ckpt_<step>.shards/`` (atomic tmp+rename per file, per-shard CRC32
  in the manifest, ``ckpt.shard_write`` chaos hook per file) and the
  manifest container commits LAST, so a kill mid-shard leaves the
  previous checkpoint the newest valid one.  ``restore_resharded``
  assembles only the blocks each target device needs
  (:func:`reshard_streamed`), never materializing the full state on one
  host; ``read_checkpoint_file`` transparently re-assembles full host
  arrays for legacy consumers.  Schema-2 files keep loading (gathered,
  with a "predates shard streaming" warning) and a re-save upgrades
  them to schema 3.
* Serve KV-block handoff: :func:`stream_kv_handoff` /
  :func:`load_kv_handoff` move one session's paged KV blocks between a
  disaggregated prefill engine and a decode engine
  (:mod:`apex_tpu.serve.disagg`) through the SAME schema-3 shard-file
  contract — per-block files (int8 payload + fp32 scales stream as
  separate parts), per-file CRC32, manifest commits last, one block's
  bytes on the host at a time, ``serve.kv_handoff`` chaos hook per
  file.  Validation splits the same way checkpoints do: partial or
  bit-rotted handoffs raise :class:`CheckpointCorruptError` (the
  coordinator discards and re-streams), mismatched pool geometry
  raises :class:`CheckpointReshardError` (a config error — no retry).
* :class:`BadStepGuard` — escalation above the ``ScalerState`` skip logic
  (`apex_tpu/amp/scaler.py`): the scaler already halves the scale and
  skips the step on overflow, silently and forever; the guard counts
  *consecutive* skipped steps and after ``patience`` of them escalates
  per policy — warn → snapshot-rollback to the last good step → raise
  :class:`TrainingDivergedError`.  Wired into the fused
  ``training.step.TrainStep`` (observes the on-device skip flag the step
  now carries in ``state.scaler.overflow``) and the eager step-cache
  surface (``guard.attach_optimizer``) without adding host syncs or
  step-cache dispatches to the clean-step hot path: flags are consumed
  lazily via ``jax.Array.is_ready`` polling, blocking only when the
  pending queue exceeds its bound (which on a healthy run it never does).

Typed failures for the distributed layer
(:class:`DistributedInitError`, :class:`CollectiveTimeoutError`) live here
too; ``apex_tpu.parallel.distributed`` raises them from its bounded-retry
init and collective-timeout wrappers.

Every failure path is exercised in tier-1 tests through the
:mod:`apex_tpu.runtime.chaos` hook points (``ckpt.mid_write``,
``ckpt.pre_rename``, ``ckpt.shard_write``, ``ckpt.reshard``,
``serve.kv_handoff``, ``train.step``, ``dist.init``,
``dist.collective``).
"""
from __future__ import annotations

import collections
import os
import pickle
import re
import shutil
import threading
import warnings
import zlib
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from . import chaos as _chaos
from ..observe import spans as _spans

#: bump when the container layout changes; readers accept <= this.
#: Schema 2 adds OPTIONAL manifest fields only (per-component "layout",
#: top-level "plan") — schema-1 files keep loading unchanged.
#: Schema 3 adds an OPTIONAL per-component "streamed" manifest entry
#: (per-shard file layout under ``ckpt_<step>.shards/``); components
#: without it are plain schema-2 gathered payloads, so schema-2 files
#: keep loading unchanged.
SCHEMA_VERSION = 3
_MAGIC = "__apex_tpu_checkpoint__"
_CKPT_RE = re.compile(r"^ckpt_(\d+)\.pkl$")
_SHARD_DIR_RE = re.compile(r"^ckpt_(\d+)\.shards$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed manifest/schema/checksum validation (partial
    write, bit rot, or a future schema).  ``restore_or_initialize`` falls
    back past these to the newest checkpoint that validates."""


class CheckpointReshardError(RuntimeError):
    """A checkpoint VALIDATED but cannot be laid out under the target
    step's plan: pytree structure, leaf shape, or leaf dtype differs —
    i.e. the checkpoint comes from a different model/optimizer config,
    not a different parallelism plan.  The message names the component
    and leaf.  Unlike :class:`CheckpointCorruptError` this is a config
    error, so elastic restore does NOT scan past it."""


class TrainingDivergedError(RuntimeError):
    """Raised by :class:`BadStepGuard` when an overflow-skip streak
    exhausts the escalation ladder: the loss scale has collapsed and the
    run is not making progress."""


class DistributedInitError(RuntimeError):
    """``init_distributed`` exhausted its retry budget / deadline."""


class CollectiveTimeoutError(RuntimeError):
    """A collective did not complete within its deadline — typically a
    missing or wedged peer; the message names the suspect ranks when the
    coordinator's presence registry can identify them."""


# ---------------------------------------------------------------------------
# the one checkpoint write path
# ---------------------------------------------------------------------------


def _to_host(tree):
    """Fetch device arrays anywhere in a pytree to host numpy (one sync,
    like ``torch.save``); everything else passes through."""
    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(conv, tree)


def _fsync_dir(path):
    # rename durability: fsync the containing directory so the new entry
    # survives power loss, not just process death (best-effort on
    # filesystems that refuse O_RDONLY dir fds)
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def capture_layout(tree) -> Optional[dict]:
    """The sharding layout of a device pytree, as plain JSON-able data
    for the schema-2 manifest.  Must run BEFORE :func:`_to_host`: the
    host transfer gathers every shard into a full numpy array and the
    layout is gone.  ``specs`` aligns with ``jax.tree_util`` leaf order —
    ``None`` for a leaf not placed on a mesh, else one entry per array
    dimension in the partition-spec prefix (``["data"]`` = dim 0 sharded
    over the "data" mesh axis, ``[]`` = replicated on the mesh).
    Returns None when no leaf carries a NamedSharding (single-device or
    already-host state: nothing to record)."""
    specs = []
    mesh = None
    for x in jax.tree_util.tree_leaves(tree):
        s = getattr(x, "sharding", None)
        if isinstance(x, jax.Array) and \
                isinstance(s, jax.sharding.NamedSharding):
            if mesh is None:
                mesh = s.mesh
            specs.append([list(p) if isinstance(p, tuple) else p
                          for p in s.spec])
        else:
            specs.append(None)
    if mesh is None:
        return None
    return {"specs": specs,
            "mesh_shape": [int(d) for d in mesh.devices.shape],
            "mesh_axes": [str(a) for a in mesh.axis_names]}


def _plan_meta(plan) -> Optional[dict]:
    """Manifest entry for the ``parallel.auto.Plan`` a state was saved
    under.  Duck-typed (anything with ``key()``/``name()`` works) so this
    module never imports the planner; rebuild with
    ``parallel.auto.plan_from_key(meta["key"], meta["n_devices"])``."""
    if plan is None:
        return None
    try:
        return {"key": list(plan.key()), "name": plan.name(),
                "zero_stage": int(getattr(plan, "zero_stage", 0)),
                "n_devices": int(getattr(plan, "n_devices", 1))}
    except Exception:
        return None


# ---------------------------------------------------------------------------
# streaming shard IO (schema 3)
# ---------------------------------------------------------------------------


class _StreamedLeaf:
    """Placeholder pickled into the container payload in place of an
    array leaf whose bytes live in per-shard files (schema 3).  Carries
    only the leaf's flat index; shape/dtype/shard layout live in the
    manifest's ``streamed`` entry."""

    __slots__ = ("idx",)

    def __init__(self, idx: int):
        self.idx = int(idx)

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"_StreamedLeaf({self.idx})"


def _shard_index_meta(index, shape) -> list:
    """Normalize a shard's index (tuple of slices from
    ``jax.Array.addressable_shards[i].index``) into JSON-able
    ``[[start, stop], ...]`` pairs, one per array dimension."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, _ = sl.indices(int(dim))
        out.append([int(start), int(stop)])
    return out


def _write_shard_file(dir_path: str, name: str, buf: bytes) -> None:
    # same durability contract as the manifest container: tmp + fsync +
    # one rename, so a shard file either exists complete or not at all
    tmp = os.path.join(dir_path, f"{name}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(buf)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, os.path.join(dir_path, name))


def stream_components_to_dir(dir_path: str, components: dict):
    """Write every ``jax.Array`` leaf in ``components`` as per-shard
    files under ``dir_path`` — one file per DISTINCT shard index
    (replicated shards dedupe to one file), raw ``tobytes()`` content,
    atomic per-file writes.  The host never holds more than one shard's
    bytes at a time; the returned peak is that high-water mark.

    Chaos hook ``ckpt.shard_write`` fires before each file — a kill
    there leaves a partial shard directory and NO manifest, which is
    exactly the debris a mid-save host loss leaves.

    Returns ``(skeletons, streamed_meta, peak_bytes)``: per-component
    pytrees with streamed leaves replaced by :class:`_StreamedLeaf`
    placeholders (everything else passes through to the pickled
    payload), the per-component manifest metadata, and the largest
    single host buffer touched."""
    os.makedirs(dir_path, exist_ok=True)
    skeletons, streamed_meta = {}, {}
    peak = 0
    for comp, tree in components.items():
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        leaf_meta, out_leaves, any_streamed = [], [], False
        comp_tag = re.sub(r"[^A-Za-z0-9_.-]", "_", comp)
        for i, leaf in enumerate(leaves):
            if not isinstance(leaf, jax.Array):
                leaf_meta.append(None)
                out_leaves.append(leaf)
                continue
            shards_meta, seen = [], set()
            for shard in leaf.addressable_shards:
                idx = _shard_index_meta(shard.index, leaf.shape)
                key = tuple(map(tuple, idx))
                if key in seen:
                    continue
                seen.add(key)
                buf = np.asarray(shard.data).tobytes()
                peak = max(peak, len(buf))
                fname = f"{comp_tag}_l{i}_s{len(shards_meta)}.bin"
                if _chaos.active():
                    _chaos.hook("ckpt.shard_write", dir=dir_path,
                                file=fname, component=comp, leaf=i)
                _write_shard_file(dir_path, fname, buf)
                shards_meta.append({"file": fname,
                                    "crc32": zlib.crc32(buf),
                                    "nbytes": len(buf), "index": idx})
            leaf_meta.append({"shape": [int(d) for d in leaf.shape],
                              "dtype": str(leaf.dtype),
                              "shards": shards_meta})
            out_leaves.append(_StreamedLeaf(i))
            any_streamed = True
        skeletons[comp] = jax.tree_util.tree_unflatten(treedef, out_leaves)
        if any_streamed:
            streamed_meta[comp] = {"dir": os.path.basename(dir_path),
                                   "leaves": leaf_meta}
        else:
            leaf_meta.clear()
    _fsync_dir(dir_path)
    return skeletons, streamed_meta, peak


def _read_shard(base_dir: str, streamed_dir: str, shard_meta: dict,
                dtype, source: str) -> np.ndarray:
    """One shard file → host array of the shard's block shape, CRC- and
    size-validated (:class:`CheckpointCorruptError` on any mismatch, and
    on a missing file — a partial shard dir must scan like a partial
    container)."""
    path = os.path.join(base_dir, streamed_dir, shard_meta["file"])
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except FileNotFoundError as e:
        raise CheckpointCorruptError(
            f"{source}: missing shard file {shard_meta['file']!r} "
            f"(partial shard directory?)") from e
    if len(buf) != shard_meta["nbytes"] or \
            zlib.crc32(buf) != shard_meta["crc32"]:
        raise CheckpointCorruptError(
            f"{source}: shard file {shard_meta['file']!r} failed checksum "
            f"validation (expected crc32={shard_meta['crc32']:#010x} over "
            f"{shard_meta['nbytes']} bytes)")
    block_shape = tuple(b - a for a, b in shard_meta["index"])
    return np.frombuffer(buf, dtype=dtype).reshape(block_shape)


def _assemble_leaf(leaf_meta: dict, base_dir: str, streamed_dir: str,
                   source: str) -> np.ndarray:
    """Full host array for one streamed leaf — the gathered path, for
    consumers that want exactly what :func:`_to_host` used to pickle."""
    shape = tuple(leaf_meta["shape"])
    dtype = np.dtype(leaf_meta["dtype"])
    out = np.empty(shape, dtype)
    for sh in leaf_meta["shards"]:
        idx = tuple(slice(a, b) for a, b in sh["index"])
        out[idx] = _read_shard(base_dir, streamed_dir, sh, dtype, source)
    return out


def _assemble_tree(skeleton, streamed_meta: dict, base_dir: str,
                   source: str):
    """Replace every :class:`_StreamedLeaf` placeholder in ``skeleton``
    with its fully-assembled host array."""
    leaves, treedef = jax.tree_util.tree_flatten(skeleton)
    leaf_meta = streamed_meta["leaves"]
    out = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, _StreamedLeaf):
            out.append(_assemble_leaf(leaf_meta[i], base_dir,
                                      streamed_meta["dir"], source))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# serve KV-block handoff (disaggregated prefill/decode)
# ---------------------------------------------------------------------------

_KV_MANIFEST = "KV_MANIFEST.pkl"
_KV_MAGIC = "__apex_tpu_kv_handoff__"


def _pool_parts(pool):
    """``[("kv", array)]`` for a plain pool, ``[("q", ...), ("scale",
    ...)]`` for the int8 :class:`~apex_tpu.inference.quant.QuantKV`
    pair — duck-typed so this module never imports serve."""
    if hasattr(pool, "q") and hasattr(pool, "scale"):
        return [("q", pool.q), ("scale", pool.scale)]
    return [("kv", pool)]


def stream_kv_handoff(dir_path: str, pool, table, *,
                      source: str = "kv_handoff",
                      extra_meta: Optional[dict] = None):
    """Stream one session's KV blocks out of a paged pool into
    ``dir_path`` under the schema-3 shard-file contract: one file per
    (block, pool-part) — raw ``tobytes()``, atomic tmp+fsync+rename,
    CRC32 in the manifest — and the manifest commits LAST, so a kill
    mid-handoff leaves debris with no manifest, never a manifest over
    missing blocks.  The host holds ONE block's bytes at a time — KV
    never round-trips through a gathered whole-pool (or whole-session)
    buffer, which is the point of the disaggregated handoff path.

    ``table`` is the session's physical block-id list, in logical
    order; logical order is what the manifest records, so the loader's
    fresh id list maps positionally.  Chaos hook ``serve.kv_handoff``
    fires before each block file.

    ``extra_meta`` rides in the manifest under ``"meta"`` — the elastic
    fleet stores a session's host-side state (generated tokens,
    pending token, position, SLO class) there, so manifest-commits-last
    covers the metadata too: a committed meta record implies committed
    KV blocks, and debris carries neither.

    Returns ``(manifest, peak_bytes)`` — peak is the largest single
    host buffer touched (the bench's ``handoff_bytes_peak_host``)."""
    os.makedirs(dir_path, exist_ok=True)
    parts = _pool_parts(pool)
    blocks_meta = []
    peak = 0
    for logical, bid in enumerate(table):
        entry = {}
        for part, buf_arr in parts:
            block = np.asarray(buf_arr[:, :, int(bid)])
            buf = block.tobytes()
            peak = max(peak, len(buf))
            fname = f"kvblk{logical}_{part}.bin"
            if _chaos.active():
                _chaos.hook("serve.kv_handoff", dir=dir_path,
                            file=fname, block=logical)
            _write_shard_file(dir_path, fname, buf)
            entry[part] = {"file": fname, "crc32": zlib.crc32(buf),
                           "nbytes": len(buf)}
        blocks_meta.append(entry)
    manifest = {
        _KV_MAGIC: SCHEMA_VERSION,
        "kind": "kv_handoff",
        "quant": len(parts) == 2,
        "parts": {part: {"shape": [int(d) for d in arr.shape[:2]]
                         + [int(d) for d in arr.shape[3:]],
                         "dtype": str(arr.dtype)}
                  for part, arr in parts},
        "n_blocks": len(blocks_meta),
        "blocks": blocks_meta,
        "source": source,
    }
    if extra_meta is not None:
        manifest["meta"] = dict(extra_meta)
    _write_shard_file(dir_path, _KV_MANIFEST, pickle.dumps(manifest))
    _fsync_dir(dir_path)
    return manifest, peak


def read_kv_handoff_meta(dir_path: str) -> dict:
    """Load and validate a KV handoff directory's MANIFEST without
    touching the block files.  The elastic serve fleet reads a lost
    session's metadata (``manifest["meta"]``) and block count here
    before allocating destination blocks — and a mid-stream kill's
    manifest-less debris is rejected here with
    :class:`CheckpointCorruptError`, never adopted."""
    src = os.path.join(dir_path, _KV_MANIFEST)
    try:
        with open(src, "rb") as f:
            manifest = pickle.loads(f.read())
    except FileNotFoundError as e:
        raise CheckpointCorruptError(
            f"{dir_path}: no KV handoff manifest (mid-handoff "
            f"kill?)") from e
    if not isinstance(manifest, dict) or \
            manifest.get(_KV_MAGIC) is None or \
            manifest.get("kind") != "kv_handoff":
        raise CheckpointCorruptError(
            f"{dir_path}: not a KV handoff manifest")
    if manifest[_KV_MAGIC] > SCHEMA_VERSION:
        raise CheckpointCorruptError(
            f"{dir_path}: handoff schema {manifest[_KV_MAGIC]} is newer "
            f"than this reader ({SCHEMA_VERSION})")
    return manifest


def load_kv_handoff(dir_path: str, pool, new_ids):
    """Scatter a streamed KV handoff into ``pool`` at the freshly
    allocated physical ids ``new_ids`` (logical order — entry i of the
    manifest lands in ``new_ids[i]``).  Bitwise: block bytes are
    written into the destination pool verbatim, int8 payloads AND
    their fp32 scales alike, so a handed-off session's continuation is
    the unified engine's continuation.

    Raises :class:`CheckpointCorruptError` when the handoff directory
    is missing its manifest (a mid-handoff kill), a block file is
    absent, or a CRC/size check fails; raises
    :class:`CheckpointReshardError` when the manifest validates but
    describes a different pool geometry (dtype, per-block shape, or
    quantization) or a different block count than ``new_ids`` — that
    is a config error, not corruption.  Returns
    ``(new_pool, peak_bytes)``."""
    manifest = read_kv_handoff_meta(dir_path)
    parts = _pool_parts(pool)
    if manifest["quant"] != (len(parts) == 2):
        raise CheckpointReshardError(
            f"{dir_path}: handoff quant={manifest['quant']} but the "
            f"destination pool is "
            f"{'int8' if len(parts) == 2 else 'dense'}")
    for part, arr in parts:
        meta = manifest["parts"][part]
        want = [int(d) for d in arr.shape[:2]] \
            + [int(d) for d in arr.shape[3:]]
        if meta["shape"] != want or meta["dtype"] != str(arr.dtype):
            raise CheckpointReshardError(
                f"{dir_path}: handoff part {part!r} is "
                f"{meta['shape']}/{meta['dtype']}, destination pool "
                f"block is {want}/{arr.dtype} — pools must share "
                f"geometry (layers/heads/block_size/head_dim/dtype)")
    new_ids = list(new_ids)
    if len(new_ids) != manifest["n_blocks"]:
        raise CheckpointReshardError(
            f"{dir_path}: handoff carries {manifest['n_blocks']} "
            f"blocks, caller allocated {len(new_ids)}")
    peak = 0
    out = {part: arr for part, arr in parts}
    for logical, entry in enumerate(manifest["blocks"]):
        nid = int(new_ids[logical])
        for part, arr in list(out.items()):
            meta = entry[part]
            path = os.path.join(dir_path, meta["file"])
            try:
                with open(path, "rb") as f:
                    buf = f.read()
            except FileNotFoundError as e:
                raise CheckpointCorruptError(
                    f"{dir_path}: missing handoff block file "
                    f"{meta['file']!r} (partial handoff "
                    f"directory?)") from e
            if len(buf) != meta["nbytes"] or \
                    zlib.crc32(buf) != meta["crc32"]:
                raise CheckpointCorruptError(
                    f"{dir_path}: handoff block file {meta['file']!r} "
                    f"failed checksum validation")
            peak = max(peak, len(buf))
            block_shape = arr.shape[:2] + arr.shape[3:]
            block = np.frombuffer(
                buf, dtype=arr.dtype).reshape(block_shape)
            out[part] = out[part].at[:, :, nid].set(block)
    if len(parts) == 2:
        new_pool = type(pool)(out["q"], out["scale"])
    else:
        new_pool = out["kv"]
    return new_pool, peak


def discard_kv_handoff(dir_path: str) -> None:
    """Remove a handoff directory — after a successful ingest, or to
    clear partial debris before a retry."""
    shutil.rmtree(dir_path, ignore_errors=True)


def serialize_checkpoint(components: dict, *, to_host: bool = True,
                         layouts: Optional[dict] = None,
                         plan=None, streamed: Optional[dict] = None) -> bytes:
    """Pickle ``components`` into the manifested container format:
    ``{_MAGIC: schema, "manifest": {...}, "payload": {name: bytes}}``.
    Each component is pickled separately so the manifest can carry a
    per-component CRC32 the loader verifies before unpickling anything.

    Schema 2: the manifest also records each component's device-side
    sharding layout (``layouts`` — captured here via
    :func:`capture_layout` when ``to_host=True`` and not supplied by the
    caller, who must capture it themselves when passing pre-fetched host
    trees) and, when ``plan`` is given, the parallel plan's structural
    identity.  This is the metadata
    :meth:`CheckpointManager.restore_resharded` reshards by.

    Schema 3: ``streamed`` (from :func:`stream_components_to_dir`) maps
    component names to their per-shard file layout; those components'
    payloads are placeholder skeletons, and the manifest entry is what
    the streaming reader resolves shard files through."""
    if layouts is None:
        layouts = {k: capture_layout(v) for k, v in components.items()}
    if to_host:
        components = {k: _to_host(v) for k, v in components.items()}
    payload = {k: pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
               for k, v in components.items()}
    comp_meta = {}
    for k, b in payload.items():
        comp_meta[k] = {"crc32": zlib.crc32(b), "nbytes": len(b)}
        if layouts.get(k) is not None:
            comp_meta[k]["layout"] = layouts[k]
        if streamed and streamed.get(k) is not None:
            comp_meta[k]["streamed"] = streamed[k]
    manifest = {"schema": SCHEMA_VERSION, "components": comp_meta}
    plan_meta = _plan_meta(plan)
    if plan_meta is not None:
        manifest["plan"] = plan_meta
    return pickle.dumps({_MAGIC: SCHEMA_VERSION, "manifest": manifest,
                         "payload": payload},
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_checkpoint(blob, *, source: str = "<bytes>",
                           return_manifest: bool = False,
                           base_dir: Optional[str] = None,
                           assemble_streamed: bool = True):
    """Validate + unpickle a container produced by
    :func:`serialize_checkpoint` (or a legacy manifest-less pickle, with a
    warning).  ``blob`` may be bytes or an already-unpickled object.
    With ``return_manifest=True`` returns ``(components, manifest)`` —
    manifest is None for legacy pickles — so elastic restore can read the
    saved layout/plan without a second parse.

    Schema-3 streamed components resolve their shard files relative to
    ``base_dir`` (the directory holding the container file — callers
    with only bytes and no directory cannot load streamed components).
    ``assemble_streamed=False`` skips the gathered re-assembly and hands
    back the placeholder skeletons, for the streaming reshard path that
    reads only the blocks each target device needs."""
    if isinstance(blob, (bytes, bytearray, memoryview)):
        try:
            obj = pickle.loads(bytes(blob))
        except Exception as e:
            raise CheckpointCorruptError(
                f"{source}: not a readable pickle "
                f"(partial write?): {e}") from e
    else:
        obj = blob
    if not (isinstance(obj, dict) and _MAGIC in obj):
        warnings.warn(
            f"{source}: legacy manifest-less checkpoint — loaded without "
            f"checksum validation (re-save with save_checkpoint / "
            f"CheckpointManager to get integrity checking)",
            stacklevel=2)
        return (obj, None) if return_manifest else obj
    schema = obj[_MAGIC]
    if not isinstance(schema, int) or schema > SCHEMA_VERSION:
        raise CheckpointCorruptError(
            f"{source}: checkpoint schema {schema!r} is newer than this "
            f"library supports (<= {SCHEMA_VERSION})")
    manifest = obj.get("manifest")
    payload = obj.get("payload")
    if not isinstance(manifest, dict) or not isinstance(payload, dict):
        raise CheckpointCorruptError(
            f"{source}: container missing manifest/payload")
    declared = manifest.get("components", {})
    if set(declared) != set(payload):
        raise CheckpointCorruptError(
            f"{source}: manifest names components "
            f"{sorted(declared)} but payload holds {sorted(payload)}")
    out = {}
    for name, blob_i in payload.items():
        meta = declared[name]
        if len(blob_i) != meta["nbytes"] or \
                zlib.crc32(blob_i) != meta["crc32"]:
            raise CheckpointCorruptError(
                f"{source}: component {name!r} failed checksum validation "
                f"(expected crc32={meta['crc32']:#010x} over "
                f"{meta['nbytes']} bytes)")
        out[name] = pickle.loads(blob_i)
        if assemble_streamed and meta.get("streamed") is not None:
            if base_dir is None:
                raise CheckpointCorruptError(
                    f"{source}: component {name!r} is shard-streamed but "
                    f"no base directory is known to resolve its shard "
                    f"files (load via read_checkpoint_file)")
            out[name] = _assemble_tree(out[name], meta["streamed"],
                                       base_dir, source)
    return (out, manifest) if return_manifest else out


def write_checkpoint_file(path: str, components: dict, *,
                          to_host: bool = True,
                          layouts: Optional[dict] = None,
                          plan=None, streamed: Optional[dict] = None) -> str:
    """Atomically write ``components`` to ``path``: serialize, write to a
    sibling tmp file, flush + fsync, then one ``os.rename``.  A crash at
    ANY point leaves ``path`` either absent or a complete previous
    checkpoint — never a partial file.  Chaos hooks: ``ckpt.mid_write``
    (payload half-written in the tmp file), ``ckpt.pre_rename`` (payload
    durable, rename pending), ``ckpt.post_rename``.  For schema-3
    streamed saves this is the COMMIT point: the shard files are already
    durable, and the rename here is what makes the checkpoint exist."""
    blob = serialize_checkpoint(components, to_host=to_host,
                                layouts=layouts, plan=plan,
                                streamed=streamed)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            mid = len(blob) // 2
            f.write(blob[:mid])
            if _chaos.active():
                _chaos.hook("ckpt.mid_write", path=path, tmp=tmp)
            f.write(blob[mid:])
            f.flush()
            os.fsync(f.fileno())
        if _chaos.active():
            _chaos.hook("ckpt.pre_rename", path=path, tmp=tmp)
        os.rename(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
        if _chaos.active():
            _chaos.hook("ckpt.post_rename", path=path)
    except _chaos.ChaosKilled:
        # simulated process death: leave the honest debris a real SIGKILL
        # would (a partial tmp file, the final path untouched) — this is
        # the state the recovery tests assert on; _sweep_tmp collects it
        # on the next manager save
        raise
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def read_checkpoint_file(path: str, *, return_manifest: bool = False,
                         assemble_streamed: bool = True):
    """Read + validate a checkpoint written by
    :func:`write_checkpoint_file` (legacy pickles load with a warning).
    Raises :class:`CheckpointCorruptError` on any validation failure and
    ``FileNotFoundError`` when ``path`` does not exist.  See
    :func:`deserialize_checkpoint` for ``return_manifest`` and
    ``assemble_streamed`` (schema-3 shard files resolve next to
    ``path``)."""
    with open(path, "rb") as f:
        blob = f.read()
    return deserialize_checkpoint(blob, source=path,
                                  return_manifest=return_manifest,
                                  base_dir=os.path.dirname(
                                      os.path.abspath(path)),
                                  assemble_streamed=assemble_streamed)


# ---------------------------------------------------------------------------
# cross-plan reshard (elastic restore)
# ---------------------------------------------------------------------------


def reshard_state(host_state, target_state, *, component: str = "state",
                  source: str = "<checkpoint>", stats_out=None):
    """Lay a host checkpoint pytree out under ``target_state``'s CURRENT
    shardings — the plan-B half of elastic restore.

    The container stores every array gathered to full host numpy
    (:func:`_to_host` runs before pickling), so "gather shards per the
    saved spec" already happened at save time; resharding is re-slicing:
    each leaf is ``jax.device_put`` under the matching target leaf's
    sharding, which hands every device exactly the shard it owns under
    the new plan.  No arithmetic touches the values, so fp32 masters
    round-trip bit-exact across any plan A → plan B.

    Sources need not be host arrays: when a source leaf is itself a
    live ``jax.Array`` whose sharding already matches the target leaf's,
    it passes through AS-IS — no host round-trip, no re-placement, the
    identical buffers (the eager cousin of the streaming-restore fix:
    layout-identical components cost zero).  Only genuinely relaid-out
    leaves pay the ``device_put``.

    Chaos hook ``ckpt.reshard`` fires once per component before any
    device placement; the path is read-only on disk, so a kill here
    leaves the checkpoint loadable by the next attempt.

    ``stats_out``, when a dict, is filled with per-leaf placement
    accounting — which leaves took the zero-copy fast path and which
    actually paid a copy: ``{"leaves", "zero_copy", "copied",
    "bytes_moved", "per_leaf": [(name, mode), ...]}`` where ``mode`` is
    ``"zero_copy"``, ``"device_put"`` or ``"host"``.  The return value
    is unchanged; callers that don't pass it pay nothing.  Elastic
    restore surfaces these in ``elastic.restore`` telemetry and the
    rollout weight-publish path in ``rollout.weight_sync`` — "zero-copy
    or priced" stops being a guess.

    Raises :class:`CheckpointReshardError` naming the component (and
    leaf, where one is identifiable) when the structures are
    incompatible — a checkpoint from a different model/optimizer config
    cannot be resharded, only retrained."""
    if _chaos.active():
        _chaos.hook("ckpt.reshard", component=component, source=source)
    tgt_paths, tgt_def = jax.tree_util.tree_flatten_with_path(target_state)
    src_leaves, src_def = jax.tree_util.tree_flatten(host_state)
    if src_def != tgt_def:
        raise CheckpointReshardError(
            f"{source}: component {component!r}: checkpoint pytree "
            f"structure does not match the target step "
            f"({src_def.num_leaves} vs {tgt_def.num_leaves} leaves) — "
            f"different model/optimizer config")
    out = []
    n_zero = n_copied = bytes_moved = 0
    per_leaf = []
    for (path, tgt), src in zip(tgt_paths, src_leaves):
        if not isinstance(tgt, jax.Array):
            out.append(src)
            continue
        name = jax.tree_util.keystr(path)
        shp = tuple(getattr(src, "shape", ()))
        if shp != tuple(tgt.shape):
            raise CheckpointReshardError(
                f"{source}: component {component!r} leaf {name}: saved "
                f"shape {shp} cannot be resharded into target shape "
                f"{tuple(tgt.shape)}")
        sdt = getattr(src, "dtype", None)
        if sdt is not None and np.dtype(sdt) != np.dtype(tgt.dtype):
            raise CheckpointReshardError(
                f"{source}: component {component!r} leaf {name}: saved "
                f"dtype {np.dtype(sdt)} != target dtype "
                f"{np.dtype(tgt.dtype)} (reshard never casts — masters "
                f"must stay bit-exact)")
        if isinstance(src, jax.Array) and not src.is_deleted():
            # layout-identical fast path: the source already holds every
            # shard where the target wants it — hand it through bit-exact
            try:
                same = src.sharding.is_equivalent_to(tgt.sharding,
                                                     src.ndim)
            except Exception:
                same = src.sharding == tgt.sharding
            if same:
                out.append(src)
                n_zero += 1
                per_leaf.append((name, "zero_copy"))
                continue
        n_copied += 1
        bytes_moved += int(np.prod(shp, dtype=np.int64)) \
            * np.dtype(tgt.dtype).itemsize
        if isinstance(tgt.sharding, jax.sharding.NamedSharding):
            per_leaf.append((name, "device_put"))
            out.append(jax.device_put(src, tgt.sharding))
        else:
            # single-device / replicated target (plain jit or the
            # shard_map tp path, whose state stays whole): re-device
            # UNCOMMITTED so the step's own dispatch placement wins —
            # committing to the fresh state's literal device would pin a
            # shard_map's replicated operand to one device and fail
            import jax.numpy as jnp
            per_leaf.append((name, "host"))
            out.append(jnp.asarray(src))
    if stats_out is not None:
        stats_out.update(leaves=n_zero + n_copied, zero_copy=n_zero,
                         copied=n_copied, bytes_moved=bytes_moved,
                         per_leaf=per_leaf)
    return jax.tree_util.tree_unflatten(tgt_def, out)


def reshard_streamed(skeleton, streamed_meta: dict, target_state, *,
                     base_dir: str, component: str = "state",
                     source: str = "<checkpoint>"):
    """Streaming half of elastic restore: lay a schema-3 shard-streamed
    component out under ``target_state``'s CURRENT shardings WITHOUT
    ever assembling the full state on the host.

    For each target leaf, each addressable device's block is assembled
    from only the overlapping source shard files
    (``sharding.devices_indices_map`` gives the target index; a slice-
    overlap copy fills the block) and placed via
    ``jax.make_array_from_callback``.  Values are copied byte-for-byte —
    the result is bitwise-equal to the gathered
    :func:`reshard_state` path on the same checkpoint.

    Same validation and chaos contract as :func:`reshard_state`
    (``ckpt.reshard`` fires once per component;
    :class:`CheckpointReshardError` on structure/shape/dtype mismatch).

    Returns ``(state, stats)`` with ``stats["peak_host_bytes"]`` the
    high-water mark of host bytes held at once — the number
    ``bench.py --cluster`` compares against the gathered path's full
    state size."""
    if _chaos.active():
        _chaos.hook("ckpt.reshard", component=component, source=source)
    tgt_paths, tgt_def = jax.tree_util.tree_flatten_with_path(target_state)
    src_leaves, src_def = jax.tree_util.tree_flatten(skeleton)
    if src_def != tgt_def:
        raise CheckpointReshardError(
            f"{source}: component {component!r}: checkpoint pytree "
            f"structure does not match the target step "
            f"({src_def.num_leaves} vs {tgt_def.num_leaves} leaves) — "
            f"different model/optimizer config")
    leaves_meta = streamed_meta["leaves"]
    stats = {"peak_host_bytes": 0, "shard_reads": 0}
    out = []
    for i, ((path, tgt), src) in enumerate(zip(tgt_paths, src_leaves)):
        if not isinstance(src, _StreamedLeaf):
            # non-array leaf (or a component mixing host leaves in):
            # defer to the gathered per-leaf semantics
            out.append(src if not isinstance(tgt, jax.Array)
                       else jax.numpy.asarray(src))
            continue
        meta = leaves_meta[i]
        name = jax.tree_util.keystr(path)
        if not isinstance(tgt, jax.Array):
            raise CheckpointReshardError(
                f"{source}: component {component!r} leaf {name}: saved "
                f"array has no array counterpart in the target step")
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        if shape != tuple(tgt.shape):
            raise CheckpointReshardError(
                f"{source}: component {component!r} leaf {name}: saved "
                f"shape {shape} cannot be resharded into target shape "
                f"{tuple(tgt.shape)}")
        if dtype != np.dtype(tgt.dtype):
            raise CheckpointReshardError(
                f"{source}: component {component!r} leaf {name}: saved "
                f"dtype {dtype} != target dtype {np.dtype(tgt.dtype)} "
                f"(reshard never casts — masters must stay bit-exact)")

        # tiny per-leaf shard cache: consecutive target blocks overlap
        # the same source files (dp 8→4: two reads per block), so cache
        # the last few loaded shards instead of re-reading the file
        cache: dict = {}

        def load_shard(sh):
            key = sh["file"]
            if key not in cache:
                if len(cache) >= 2:
                    cache.pop(next(iter(cache)))
                cache[key] = _read_shard(base_dir, streamed_meta["dir"],
                                         sh, dtype, source)
                stats["shard_reads"] += 1
            return cache[key]

        def build_block(index):
            norm = [(sl.indices(int(d))[0], sl.indices(int(d))[1])
                    for sl, d in zip(index, shape)]
            block = np.empty(tuple(b - a for a, b in norm), dtype)
            for sh in meta["shards"]:
                dst, srcs = [], []
                for (t0, t1), (s0, s1) in zip(norm, sh["index"]):
                    lo, hi = max(t0, s0), min(t1, s1)
                    if hi <= lo:
                        break
                    dst.append(slice(lo - t0, hi - t0))
                    srcs.append(slice(lo - s0, hi - s0))
                else:
                    block[tuple(dst)] = load_shard(sh)[tuple(srcs)]
            held = block.nbytes + sum(a.nbytes for a in cache.values())
            stats["peak_host_bytes"] = max(stats["peak_host_bytes"], held)
            return block

        sharding = tgt.sharding
        if isinstance(sharding, jax.sharding.NamedSharding):
            out.append(jax.make_array_from_callback(shape, sharding,
                                                    build_block))
        else:
            # single-device / replicated target: one full-leaf block,
            # re-deviced UNCOMMITTED (same rationale as reshard_state)
            full = build_block(tuple(slice(0, d) for d in shape))
            out.append(jax.numpy.asarray(full))
        cache.clear()
    return jax.tree_util.tree_unflatten(tgt_def, out), stats


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------


class SaveHandle:
    """Error-surfacing handle for one (possibly async) save.

    ``wait()`` blocks until the write is durable and re-raises anything
    the background thread hit — a save error silently swallowed is a run
    that discovers at *restore* time it has no checkpoints."""

    def __init__(self, step: int, path: str):
        self.step = step
        self.path = path
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None

    def _finish(self, exc: Optional[BaseException] = None):
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint save for step {self.step} still in flight "
                f"after {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self.path


class CheckpointManager:
    """Atomic, rolling, optionally-async checkpoints under one directory.

    Layout: ``<directory>/ckpt_<step>.pkl`` in the manifested container
    format of :func:`write_checkpoint_file`.  ``keep_n`` newest VALID-path
    files are retained; retention runs after each successful save and
    never deletes the checkpoint just written.

    ``save(step=n, **components)`` is synchronous; ``save_async`` fetches
    device arrays to host on the caller thread (the same one sync the
    blocking path pays — mandatory: the caller may donate/overwrite the
    device buffers on the very next step) and returns a
    :class:`SaveHandle` while a single background worker pickles and
    writes.  One save is in flight at a time; a second ``save_async``
    enqueues behind it.  Call :meth:`wait` (or :meth:`close`, or use as a
    context manager) before reading checkpoints or exiting.

    :meth:`restore_or_initialize` is the preemption-safe resume entry:
    scan newest→oldest, skip anything that fails validation (the partial
    tmp files an interrupted save leaves are never even candidates — the
    atomic rename means an invalid *final* file can only be bit rot), and
    fall back to ``initialize`` when nothing valid exists.
    """

    def __init__(self, directory: str, keep_n: int = 3):
        if keep_n < 1:
            raise ValueError(f"keep_n must be >= 1, got {keep_n}")
        self.directory = directory
        self.keep_n = keep_n
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        #: filled by save_sharded / restore_resharded — the host-memory
        #: numbers bench.py --cluster reports
        self.last_save_stats: dict = {}
        self.last_restore_stats: dict = {}

    # -- paths -------------------------------------------------------------
    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{int(step):08d}.pkl")

    def shard_dir_for(self, step: int) -> str:
        """Schema-3 shard-file directory for ``step`` (exists only for
        checkpoints written by :meth:`save_sharded`)."""
        return os.path.join(self.directory, f"ckpt_{int(step):08d}.shards")

    def all_steps(self) -> list:
        """Step numbers with a (final-path) checkpoint file, ascending.
        Presence only — validity is decided at restore time."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _sweep_tmp(self):
        # debris from killed writers (ours or a predecessor's): partial
        # container tmp files, partial shard tmp files, and shard
        # directories whose manifest never committed (a kill mid-shard
        # leaves the dir with no ckpt_<step>.pkl — the previous
        # checkpoint is still the newest valid one)
        names = os.listdir(self.directory)
        final = set(names)
        for name in names:
            path = os.path.join(self.directory, name)
            if ".pkl.tmp." in name:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            m = _SHARD_DIR_RE.match(name)
            if m:
                if f"ckpt_{m.group(1)}.pkl" not in final:
                    shutil.rmtree(path, ignore_errors=True)
                    continue
                try:
                    for sub in os.listdir(path):
                        if ".bin.tmp." in sub:
                            os.unlink(os.path.join(path, sub))
                except OSError:
                    pass

    def _retain(self, just_wrote: int):
        steps = self.all_steps()
        for s in steps[:-self.keep_n] if len(steps) > self.keep_n else []:
            if s == just_wrote:
                continue
            try:
                os.unlink(self.path_for(s))
            except OSError:
                pass
            shutil.rmtree(self.shard_dir_for(s), ignore_errors=True)

    # -- save --------------------------------------------------------------
    def _write(self, step: int, host_components: dict,
               layouts: Optional[dict] = None, plan=None,
               streamed: Optional[dict] = None, sweep: bool = True) -> str:
        if sweep:
            self._sweep_tmp()
        path = write_checkpoint_file(self.path_for(step), host_components,
                                     to_host=False, layouts=layouts,
                                     plan=plan, streamed=streamed)
        self._retain(step)
        return path

    def save(self, step: int, /, **components) -> str:
        """Blocking atomic save; returns the final path.  Sharding
        layouts are captured (before the host fetch) into the schema-2
        manifest whenever components carry mesh-placed arrays."""
        handle = SaveHandle(step, self.path_for(step))
        layouts = {k: capture_layout(v) for k, v in components.items()}
        try:
            with _spans.span("ckpt.save", step=step, mode="sync"):
                self._write(step,
                            {k: _to_host(v) for k, v in components.items()},
                            layouts=layouts)
        except BaseException as e:
            handle._finish(e)
            raise
        handle._finish()
        return handle.path

    def save_sharded(self, step: int, train_step, /, **extra) -> str:
        """Blocking atomic save of a live train step WITH its elastic
        metadata: component ``"state"`` is ``train_step.state``, and the
        manifest records each leaf's partition spec plus the step's
        parallel plan (``train_step.plan``) — everything
        :meth:`restore_resharded` needs to load this checkpoint into a
        DIFFERENT plan after the device set changes.  Extra components
        (epoch counters, rng, ...) ride along as in :meth:`save`.

        Schema 3: the state never gathers onto the host.  Each distinct
        array shard streams straight to its own file under
        :meth:`shard_dir_for` (per-shard CRC, atomic per-file writes,
        ``ckpt.shard_write`` chaos hook per file); the manifest container
        commits LAST, so a kill mid-shard leaves only an orphan shard
        directory (collected by the next save's sweep) and the previous
        checkpoint stays the newest valid one.
        ``last_save_stats["shard_bytes_peak_host"]`` records the largest
        single host buffer the save touched."""
        if "state" in extra:
            raise ValueError("save_sharded owns the 'state' component; "
                             "pass other data under different names")
        components = {"state": train_step.state, **extra}
        layouts = {k: capture_layout(v) for k, v in components.items()}
        handle = SaveHandle(step, self.path_for(step))
        try:
            with _spans.span("ckpt.save", step=step, mode="sharded"):
                self._sweep_tmp()
                sdir = self.shard_dir_for(step)
                if os.path.isdir(sdir):   # same-step re-save: fresh dir
                    shutil.rmtree(sdir, ignore_errors=True)
                skeletons, streamed, peak = \
                    stream_components_to_dir(sdir, components)
                self.last_save_stats = {"shard_bytes_peak_host": peak}
                self._write(step, skeletons, layouts=layouts,
                            plan=getattr(train_step, "plan", None),
                            streamed=streamed, sweep=False)
        except BaseException as e:
            handle._finish(e)
            raise
        handle._finish()
        return handle.path

    def save_async(self, step: int, /, **components) -> SaveHandle:
        """Async atomic save.  Device→host transfer happens HERE, on the
        caller thread (so the step loop may immediately reuse/donate the
        device buffers); pickling + IO run on the manager's worker
        thread.  Returns a :class:`SaveHandle`; errors surface on its
        ``wait()`` (and on :meth:`wait`/:meth:`close`)."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        layouts = {k: capture_layout(v) for k, v in components.items()}
        # the caller-thread cost of an async save is exactly this fetch —
        # span it separately from the worker's write
        with _spans.span("ckpt.save.submit", step=step):
            host = {k: _to_host(v) for k, v in components.items()}
        handle = SaveHandle(step, self.path_for(step))
        with self._lock:
            self._queue.append((step, host, layouts, handle))
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain, name="apex-tpu-ckpt-writer",
                    daemon=True)
                self._worker.start()
        return handle

    def _drain(self):
        while True:
            with self._lock:
                if not self._queue:
                    return
                step, host, layouts, handle = self._queue.popleft()
            try:
                with _spans.span("ckpt.save", step=step, mode="async"):
                    self._write(step, host, layouts=layouts)
            except BaseException as e:  # surfaced via handle.wait()
                handle._finish(e)
            else:
                handle._finish()

    def wait(self):
        """Block until every queued save is durable; re-raise the first
        error encountered (each handle also carries its own)."""
        while True:
            with self._lock:
                pending = list(self._queue)
                worker = self._worker
            if worker is not None:
                worker.join()
            with self._lock:
                if not self._queue and (self._worker is None
                                        or not self._worker.is_alive()):
                    break
        for *_, handle in pending:
            if handle.done() and handle._exc is not None:
                raise handle._exc

    def close(self):
        self.wait()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- restore -----------------------------------------------------------
    def restore(self, step: Optional[int] = None, *,
                return_manifest: bool = False):
        """Load + validate one checkpoint (latest when ``step`` is
        None).  See :func:`deserialize_checkpoint` for
        ``return_manifest``."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory!r}")
        with _spans.span("ckpt.restore", step=step):
            return read_checkpoint_file(self.path_for(step),
                                        return_manifest=return_manifest)

    def restore_resharded(self, train_step, step: Optional[int] = None):
        """Elastic restore: load one checkpoint (latest when ``step`` is
        None) into ``train_step``'s CURRENT layout, whatever plan it was
        saved under, and return ``(step_no, extras)`` — the non-"state"
        components.  ``train_step.state`` is replaced in place via
        :func:`reshard_state`.

        Schema-3 checkpoints stream: each target device's block is
        assembled from only the overlapping shard files
        (:func:`reshard_streamed`) — the full state never materializes
        on this host, and ``last_restore_stats`` records the mode and
        the host-bytes high-water mark.  A schema-2 checkpoint predates
        shard streaming; its arrays were gathered at save time, so it
        restores through the gathered :func:`reshard_state` path with a
        warning (re-save to upgrade it to schema 3).  A legacy /
        schema-1 checkpoint additionally carries no sharding metadata.
        Raises :class:`CheckpointReshardError` when the checkpoint is
        structurally incompatible with the step and
        :class:`CheckpointCorruptError` when it fails validation."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory!r}")
        path = self.path_for(step)
        with _spans.span("ckpt.restore", step=step, mode="resharded"):
            comps, manifest = read_checkpoint_file(
                path, return_manifest=True, assemble_streamed=False)
            if "state" not in comps:
                raise CheckpointReshardError(
                    f"{path}: no 'state' component to reshard "
                    f"(components: {sorted(comps)}) — written by "
                    f"save_sharded / ElasticTrainer.save?")
            schema = (manifest or {}).get("schema", 0)
            comp_meta = (manifest or {}).get("components", {})
            streamed = (comp_meta.get("state") or {}).get("streamed")
            if schema < 2:
                warnings.warn(
                    f"{path}: schema-{schema or 'legacy'} checkpoint "
                    f"predates sharding metadata — restoring its "
                    f"(gathered, full) arrays into the target layout "
                    f"without save-side validation", stacklevel=2)
            elif streamed is None:
                warnings.warn(
                    f"{path}: schema-{schema} checkpoint predates shard "
                    f"streaming — gathered restore (re-save to upgrade "
                    f"it to the schema-3 per-shard layout)", stacklevel=2)
            if streamed is not None:
                train_step.state, stats = reshard_streamed(
                    comps["state"], streamed, train_step.state,
                    base_dir=self.directory, component="state",
                    source=path)
                self.last_restore_stats = {"mode": "streamed",
                                           "schema": schema, **stats}
            else:
                host_state = comps["state"]
                gathered = sum(
                    x.nbytes for x in
                    jax.tree_util.tree_leaves(host_state)
                    if isinstance(x, np.ndarray))
                rs: dict = {}
                train_step.state = reshard_state(
                    host_state, train_step.state, component="state",
                    source=path, stats_out=rs)
                self.last_restore_stats = {
                    "mode": "gathered", "schema": schema,
                    "peak_host_bytes": gathered,
                    "zero_copy_leaves": rs.get("zero_copy", 0),
                    "copied_leaves": rs.get("copied", 0),
                    "reshard_bytes_moved": rs.get("bytes_moved", 0)}
            extras = {}
            for k, v in comps.items():
                if k == "state":
                    continue
                k_streamed = (comp_meta.get(k) or {}).get("streamed")
                if k_streamed is not None:   # small ride-along arrays
                    v = _assemble_tree(v, k_streamed, self.directory,
                                       path)
                extras[k] = v
        return step, extras

    def restore_or_initialize(self, initialize: Optional[Callable] = None):
        """Auto-resume: ``(step, components)`` from the newest checkpoint
        that VALIDATES, scanning past corrupt/partial ones with a warning;
        ``(None, initialize())`` — or ``(None, None)`` — when no valid
        checkpoint exists.  This is the call a preempted job makes
        unconditionally at startup."""
        for step in reversed(self.all_steps()):
            try:
                return step, self.restore(step)
            except CheckpointCorruptError as e:
                warnings.warn(
                    f"skipping corrupt checkpoint for step {step}: {e}",
                    stacklevel=2)
            except FileNotFoundError:
                continue
        return None, (initialize() if initialize is not None else None)


# ---------------------------------------------------------------------------
# BadStepGuard
# ---------------------------------------------------------------------------


def snapshot_state(state):
    """Host copy of a device-state pytree (one sync) — the rollback
    anchor :class:`BadStepGuard` refreshes on clean steps."""
    return jax.tree_util.tree_map(
        lambda x: np.array(x) if isinstance(x, jax.Array) else x, state)


def restore_state(host_state):
    """Re-device a :func:`snapshot_state` copy."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
        host_state)


class BadStepGuard:
    """Escalation above the scaler's silent skip loop.

    ``ScalerState`` handles a *transient* overflow correctly — halve the
    scale, skip the step, move on.  What it cannot see is a *storm*: a
    diverging run overflows every step, the scale collapses to
    ``min_loss_scale``, and training silently stops making progress while
    burning pod-hours.  The guard watches consecutive skipped steps and
    after ``patience`` of them escalates through ``policy`` — one stage
    per escalation event, last stage sticky:

    * ``"warn"`` — log loudly, keep going (storms sometimes pass);
    * ``"rollback"`` — restore the last known-good snapshot (params,
      optimizer slots, step counter; the CURRENT — already-halved — loss
      scale is kept so the same storm is not immediately re-entered) and
      continue;
    * ``"raise"`` — :class:`TrainingDivergedError`; let the operator (or
      the auto-resume wrapper) decide.

    Clean-path cost: ``observe`` appends the step's on-device skip flag
    (an i32 scalar the fused step already computes) to a deque and
    consumes only flags whose buffers report ``is_ready()`` — no host
    sync, no extra dispatch (verified against ``step_cache.stats()``).
    Blocking reads happen only when the pending deque exceeds
    ``max_pending`` (default ``4 * patience``) — i.e. only under storms,
    where a sync is the least of the run's problems.

    Both surfaces dispatch through ``runtime.executor`` now, so the
    guard is step-kind agnostic: the skip flag it observes rides the
    same carry whether the program is the fused ``train_step``, the
    GSPMD ``zero_train_step``, or an eager optimizer program.

    Fused path::

        guard = BadStepGuard(patience=8, policy=("warn", "rollback",
                                                 "raise"))
        guard.attach(step)           # TrainStep notifies the guard per call
        for x, y in loader:
            loss = step(x, y)        # guard escalates as configured

    Eager step-cache path (``amp.initialize`` + ``optimizer.step()``)::

        guard.attach_optimizer(optimizer)   # observes the scaler skip flag
    """

    def __init__(self, patience: int = 5,
                 policy: Sequence[str] | str = ("warn", "rollback", "raise"),
                 snapshot_interval: int = 100,
                 max_pending: Optional[int] = None,
                 on_event: Optional[Callable] = None):
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        if isinstance(policy, str):
            policy = (policy,)
        policy = tuple(policy)
        for stage in policy:
            if stage not in ("warn", "rollback", "raise"):
                raise ValueError(f"unknown guard policy stage {stage!r}")
        if not policy:
            raise ValueError("policy must name at least one stage")
        self.patience = patience
        self.policy = policy
        self.snapshot_interval = snapshot_interval
        self.max_pending = (4 * patience if max_pending is None
                            else max_pending)
        self.on_event = on_event
        self._pending: collections.deque = collections.deque()
        self._streak = 0
        self._escalations = 0
        self._clean_since_snapshot = 0
        self._snapshot = None
        self._step = None       # attached TrainStep (fused path)
        self.stats = {"observed": 0, "skipped": 0, "escalations": 0,
                      "rollbacks": 0}

    # -- wiring ------------------------------------------------------------
    def attach(self, train_step):
        """Attach to a fused ``TrainStep`` (or any object with a mutable
        ``.state`` carrying ``scaler.overflow``): the step notifies the
        guard after each call; an initial rollback snapshot is taken now."""
        self._step = train_step
        train_step._guard = self
        if "rollback" in self.policy:
            self._snapshot = snapshot_state(train_step.state)
        return train_step

    def attach_optimizer(self, optimizer):
        """Attach to an amp-processed optimizer on the eager step-cache
        surface.  The skip flag comes for free on both eager modes: under
        ``defer_scale_update=True`` it is the deferred scaler's on-device
        overflow flag (captured BEFORE the step program donates it — no
        added sync, no added dispatch); in reference-exact mode the skip
        decision is already host-known — ``scale_loss``'s one-shot
        ``skip_step`` patch REPLACES the wrapper below for skipped calls,
        so it notifies ``stash._guard`` directly (amp/handle.py).
        Rollback needs a state snapshot the eager surface does not own,
        so the rollback stage degrades to warn here unless the caller
        layers its own snapshot management."""
        guard = self
        stash = getattr(optimizer, "_amp_stash", None)
        if stash is not None:
            stash._guard = self
        orig_step = optimizer.step

        def guarded_step(closure=None):
            flag = 0
            if stash is not None:
                deferred = getattr(stash, "_deferred_scaler", None)
                if deferred is not None:
                    flag = deferred.state.overflow
            ret = orig_step() if closure is None else orig_step(closure)
            guard.observe(flag)
            return ret

        optimizer.step = guarded_step
        return optimizer

    # -- observation -------------------------------------------------------
    def observe(self, skip_flag):
        """Record one step's skip flag (device i32 scalar, python int, or
        bool).  Device flags are consumed lazily — see class docstring."""
        self.stats["observed"] += 1
        self._pending.append(skip_flag)
        self._drain(block=False)
        while len(self._pending) > self.max_pending:
            self._consume(self._pending.popleft())

    def flush(self):
        """Consume every pending flag (blocking).  Call at loop end, or
        before trusting ``stats`` in a test."""
        self._drain(block=True)

    def _drain(self, block: bool):
        while self._pending:
            flag = self._pending[0]
            if not block:
                ready = getattr(flag, "is_ready", None)
                if ready is not None and not ready():
                    return
            self._consume(self._pending.popleft())

    def _consume(self, flag):
        skipped = bool(int(flag))
        if skipped:
            self.stats["skipped"] += 1
            self._streak += 1
            self._clean_since_snapshot = 0
            if self._streak >= self.patience:
                self._streak = 0
                self._escalate()
        else:
            self._streak = 0
            self._clean_since_snapshot += 1
            if (self._step is not None and "rollback" in self.policy
                    and self._clean_since_snapshot
                    >= self.snapshot_interval):
                self._refresh_snapshot()

    def _refresh_snapshot(self):
        # the pending deque is empty here (we are inside a drain), so the
        # current state is at least as new as every observed flag;
        # snapshotting it can only capture MORE confirmed-clean steps
        self._snapshot = snapshot_state(self._step.state)
        self._clean_since_snapshot = 0

    # -- escalation --------------------------------------------------------
    def _escalate(self):
        stage = self.policy[min(self._escalations, len(self.policy) - 1)]
        self._escalations += 1
        self.stats["escalations"] += 1
        event = {"stage": stage, "escalation": self._escalations,
                 "patience": self.patience}
        if self.on_event is not None:
            self.on_event(event)
        msg = (f"BadStepGuard: {self.patience} consecutive overflow-skipped "
               f"steps (escalation #{self._escalations}, stage {stage!r})")
        if stage == "raise":
            raise TrainingDivergedError(
                msg + " — loss scale has collapsed; training is diverging")
        warnings.warn(msg, stacklevel=3)
        if stage == "rollback":
            self._rollback()

    def _rollback(self):
        if self._step is None or self._snapshot is None:
            warnings.warn(
                "BadStepGuard: rollback requested but no snapshot is "
                "available (eager surface, or attach() not called) — "
                "degrading to warn", stacklevel=4)
            return
        restored = restore_state(self._snapshot)
        current = self._step.state
        # keep the CURRENT (post-halving) loss scale: restoring the
        # snapshot's larger scale would walk straight back into the storm
        if hasattr(restored, "scaler") and hasattr(current, "scaler"):
            restored = restored._replace(
                scaler=restored.scaler._replace(
                    loss_scale=current.scaler.loss_scale,
                    unskipped=jax.numpy.zeros((), jax.numpy.int32),
                    overflow=jax.numpy.zeros((), jax.numpy.int32)))
        self._step.state = restored
        self.stats["rollbacks"] += 1
