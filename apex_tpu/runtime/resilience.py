"""Resilience runtime: atomic/async checkpointing, preemption-safe
auto-resume, and escalation on overflow storms.

The reference documents a "bitwise accurate" save/resume workflow
(README.md:59-99 there) but its durability story ends at ``torch.save``:
a preemption mid-write corrupts the only copy, and nothing validates a
checkpoint before unpickling it.  On a TPU pod, preemption is routine —
this module makes the save/resume loop survive it:

* :func:`write_checkpoint_file` / :func:`read_checkpoint_file` — THE one
  checkpoint write path (the legacy ``apex_tpu.utils.save_checkpoint``
  delegates here).  Writes are atomic (tmp file + fsync + ``os.rename``);
  every file carries a manifest (schema version + per-component CRC32
  checksums) validated on load, raising the typed
  :class:`CheckpointCorruptError` instead of feeding garbage to
  ``load_state_dict``.  Pre-manifest pickles still load, with a warning.
* :class:`CheckpointManager` — rolling ``keep_n`` retention over a
  directory of step-numbered checkpoints, synchronous or async save
  (device→host transfer on the caller thread — one sync, exactly like the
  blocking path — then pickling + IO on a background thread behind a
  :class:`SaveHandle` that surfaces errors on ``wait()``), and
  :meth:`CheckpointManager.restore_or_initialize` auto-resume that scans
  newest→oldest past corrupt/partial checkpoints to the latest *valid*
  one.
* :class:`BadStepGuard` — escalation above the ``ScalerState`` skip logic
  (`apex_tpu/amp/scaler.py`): the scaler already halves the scale and
  skips the step on overflow, silently and forever; the guard counts
  *consecutive* skipped steps and after ``patience`` of them escalates
  per policy — warn → snapshot-rollback to the last good step → raise
  :class:`TrainingDivergedError`.  Wired into the fused
  ``training.step.TrainStep`` (observes the on-device skip flag the step
  now carries in ``state.scaler.overflow``) and the eager step-cache
  surface (``guard.attach_optimizer``) without adding host syncs or
  step-cache dispatches to the clean-step hot path: flags are consumed
  lazily via ``jax.Array.is_ready`` polling, blocking only when the
  pending queue exceeds its bound (which on a healthy run it never does).

Typed failures for the distributed layer
(:class:`DistributedInitError`, :class:`CollectiveTimeoutError`) live here
too; ``apex_tpu.parallel.distributed`` raises them from its bounded-retry
init and collective-timeout wrappers.

Every failure path is exercised in tier-1 tests through the
:mod:`apex_tpu.runtime.chaos` hook points (``ckpt.mid_write``,
``ckpt.pre_rename``, ``train.step``, ``dist.init``, ``dist.collective``).
"""
from __future__ import annotations

import collections
import os
import pickle
import re
import threading
import warnings
import zlib
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from . import chaos as _chaos

#: bump when the container layout changes; readers accept <= this
SCHEMA_VERSION = 1
_MAGIC = "__apex_tpu_checkpoint__"
_CKPT_RE = re.compile(r"^ckpt_(\d+)\.pkl$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed manifest/schema/checksum validation (partial
    write, bit rot, or a future schema).  ``restore_or_initialize`` falls
    back past these to the newest checkpoint that validates."""


class TrainingDivergedError(RuntimeError):
    """Raised by :class:`BadStepGuard` when an overflow-skip streak
    exhausts the escalation ladder: the loss scale has collapsed and the
    run is not making progress."""


class DistributedInitError(RuntimeError):
    """``init_distributed`` exhausted its retry budget / deadline."""


class CollectiveTimeoutError(RuntimeError):
    """A collective did not complete within its deadline — typically a
    missing or wedged peer; the message names the suspect ranks when the
    coordinator's presence registry can identify them."""


# ---------------------------------------------------------------------------
# the one checkpoint write path
# ---------------------------------------------------------------------------


def _to_host(tree):
    """Fetch device arrays anywhere in a pytree to host numpy (one sync,
    like ``torch.save``); everything else passes through."""
    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(conv, tree)


def _fsync_dir(path):
    # rename durability: fsync the containing directory so the new entry
    # survives power loss, not just process death (best-effort on
    # filesystems that refuse O_RDONLY dir fds)
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def serialize_checkpoint(components: dict, *, to_host: bool = True) -> bytes:
    """Pickle ``components`` into the manifested container format:
    ``{_MAGIC: schema, "manifest": {...}, "payload": {name: bytes}}``.
    Each component is pickled separately so the manifest can carry a
    per-component CRC32 the loader verifies before unpickling anything."""
    if to_host:
        components = {k: _to_host(v) for k, v in components.items()}
    payload = {k: pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
               for k, v in components.items()}
    manifest = {
        "schema": SCHEMA_VERSION,
        "components": {k: {"crc32": zlib.crc32(b), "nbytes": len(b)}
                       for k, b in payload.items()},
    }
    return pickle.dumps({_MAGIC: SCHEMA_VERSION, "manifest": manifest,
                         "payload": payload},
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_checkpoint(blob, *, source: str = "<bytes>") -> dict:
    """Validate + unpickle a container produced by
    :func:`serialize_checkpoint` (or a legacy manifest-less pickle, with a
    warning).  ``blob`` may be bytes or an already-unpickled object."""
    if isinstance(blob, (bytes, bytearray, memoryview)):
        try:
            obj = pickle.loads(bytes(blob))
        except Exception as e:
            raise CheckpointCorruptError(
                f"{source}: not a readable pickle "
                f"(partial write?): {e}") from e
    else:
        obj = blob
    if not (isinstance(obj, dict) and _MAGIC in obj):
        warnings.warn(
            f"{source}: legacy manifest-less checkpoint — loaded without "
            f"checksum validation (re-save with save_checkpoint / "
            f"CheckpointManager to get integrity checking)",
            stacklevel=2)
        return obj
    schema = obj[_MAGIC]
    if not isinstance(schema, int) or schema > SCHEMA_VERSION:
        raise CheckpointCorruptError(
            f"{source}: checkpoint schema {schema!r} is newer than this "
            f"library supports (<= {SCHEMA_VERSION})")
    manifest = obj.get("manifest")
    payload = obj.get("payload")
    if not isinstance(manifest, dict) or not isinstance(payload, dict):
        raise CheckpointCorruptError(
            f"{source}: container missing manifest/payload")
    declared = manifest.get("components", {})
    if set(declared) != set(payload):
        raise CheckpointCorruptError(
            f"{source}: manifest names components "
            f"{sorted(declared)} but payload holds {sorted(payload)}")
    out = {}
    for name, blob_i in payload.items():
        meta = declared[name]
        if len(blob_i) != meta["nbytes"] or \
                zlib.crc32(blob_i) != meta["crc32"]:
            raise CheckpointCorruptError(
                f"{source}: component {name!r} failed checksum validation "
                f"(expected crc32={meta['crc32']:#010x} over "
                f"{meta['nbytes']} bytes)")
        out[name] = pickle.loads(blob_i)
    return out


def write_checkpoint_file(path: str, components: dict, *,
                          to_host: bool = True) -> str:
    """Atomically write ``components`` to ``path``: serialize, write to a
    sibling tmp file, flush + fsync, then one ``os.rename``.  A crash at
    ANY point leaves ``path`` either absent or a complete previous
    checkpoint — never a partial file.  Chaos hooks: ``ckpt.mid_write``
    (payload half-written in the tmp file), ``ckpt.pre_rename`` (payload
    durable, rename pending), ``ckpt.post_rename``."""
    blob = serialize_checkpoint(components, to_host=to_host)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            mid = len(blob) // 2
            f.write(blob[:mid])
            if _chaos.active():
                _chaos.hook("ckpt.mid_write", path=path, tmp=tmp)
            f.write(blob[mid:])
            f.flush()
            os.fsync(f.fileno())
        if _chaos.active():
            _chaos.hook("ckpt.pre_rename", path=path, tmp=tmp)
        os.rename(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
        if _chaos.active():
            _chaos.hook("ckpt.post_rename", path=path)
    except _chaos.ChaosKilled:
        # simulated process death: leave the honest debris a real SIGKILL
        # would (a partial tmp file, the final path untouched) — this is
        # the state the recovery tests assert on; _sweep_tmp collects it
        # on the next manager save
        raise
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def read_checkpoint_file(path: str) -> dict:
    """Read + validate a checkpoint written by
    :func:`write_checkpoint_file` (legacy pickles load with a warning).
    Raises :class:`CheckpointCorruptError` on any validation failure and
    ``FileNotFoundError`` when ``path`` does not exist."""
    with open(path, "rb") as f:
        blob = f.read()
    return deserialize_checkpoint(blob, source=path)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------


class SaveHandle:
    """Error-surfacing handle for one (possibly async) save.

    ``wait()`` blocks until the write is durable and re-raises anything
    the background thread hit — a save error silently swallowed is a run
    that discovers at *restore* time it has no checkpoints."""

    def __init__(self, step: int, path: str):
        self.step = step
        self.path = path
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None

    def _finish(self, exc: Optional[BaseException] = None):
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint save for step {self.step} still in flight "
                f"after {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self.path


class CheckpointManager:
    """Atomic, rolling, optionally-async checkpoints under one directory.

    Layout: ``<directory>/ckpt_<step>.pkl`` in the manifested container
    format of :func:`write_checkpoint_file`.  ``keep_n`` newest VALID-path
    files are retained; retention runs after each successful save and
    never deletes the checkpoint just written.

    ``save(step=n, **components)`` is synchronous; ``save_async`` fetches
    device arrays to host on the caller thread (the same one sync the
    blocking path pays — mandatory: the caller may donate/overwrite the
    device buffers on the very next step) and returns a
    :class:`SaveHandle` while a single background worker pickles and
    writes.  One save is in flight at a time; a second ``save_async``
    enqueues behind it.  Call :meth:`wait` (or :meth:`close`, or use as a
    context manager) before reading checkpoints or exiting.

    :meth:`restore_or_initialize` is the preemption-safe resume entry:
    scan newest→oldest, skip anything that fails validation (the partial
    tmp files an interrupted save leaves are never even candidates — the
    atomic rename means an invalid *final* file can only be bit rot), and
    fall back to ``initialize`` when nothing valid exists.
    """

    def __init__(self, directory: str, keep_n: int = 3):
        if keep_n < 1:
            raise ValueError(f"keep_n must be >= 1, got {keep_n}")
        self.directory = directory
        self.keep_n = keep_n
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    # -- paths -------------------------------------------------------------
    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{int(step):08d}.pkl")

    def all_steps(self) -> list:
        """Step numbers with a (final-path) checkpoint file, ascending.
        Presence only — validity is decided at restore time."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _sweep_tmp(self):
        # debris from killed writers (ours or a predecessor's)
        for name in os.listdir(self.directory):
            if ".pkl.tmp." in name:
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass

    def _retain(self, just_wrote: int):
        steps = self.all_steps()
        for s in steps[:-self.keep_n] if len(steps) > self.keep_n else []:
            if s == just_wrote:
                continue
            try:
                os.unlink(self.path_for(s))
            except OSError:
                pass

    # -- save --------------------------------------------------------------
    def _write(self, step: int, host_components: dict) -> str:
        self._sweep_tmp()
        path = write_checkpoint_file(self.path_for(step), host_components,
                                     to_host=False)
        self._retain(step)
        return path

    def save(self, step: int, /, **components) -> str:
        """Blocking atomic save; returns the final path."""
        handle = SaveHandle(step, self.path_for(step))
        try:
            self._write(step, {k: _to_host(v) for k, v in components.items()})
        except BaseException as e:
            handle._finish(e)
            raise
        handle._finish()
        return handle.path

    def save_async(self, step: int, /, **components) -> SaveHandle:
        """Async atomic save.  Device→host transfer happens HERE, on the
        caller thread (so the step loop may immediately reuse/donate the
        device buffers); pickling + IO run on the manager's worker
        thread.  Returns a :class:`SaveHandle`; errors surface on its
        ``wait()`` (and on :meth:`wait`/:meth:`close`)."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        host = {k: _to_host(v) for k, v in components.items()}
        handle = SaveHandle(step, self.path_for(step))
        with self._lock:
            self._queue.append((step, host, handle))
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain, name="apex-tpu-ckpt-writer",
                    daemon=True)
                self._worker.start()
        return handle

    def _drain(self):
        while True:
            with self._lock:
                if not self._queue:
                    return
                step, host, handle = self._queue.popleft()
            try:
                self._write(step, host)
            except BaseException as e:  # surfaced via handle.wait()
                handle._finish(e)
            else:
                handle._finish()

    def wait(self):
        """Block until every queued save is durable; re-raise the first
        error encountered (each handle also carries its own)."""
        while True:
            with self._lock:
                pending = list(self._queue)
                worker = self._worker
            if worker is not None:
                worker.join()
            with self._lock:
                if not self._queue and (self._worker is None
                                        or not self._worker.is_alive()):
                    break
        for _, _, handle in pending:
            if handle.done() and handle._exc is not None:
                raise handle._exc

    def close(self):
        self.wait()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- restore -----------------------------------------------------------
    def restore(self, step: Optional[int] = None) -> dict:
        """Load + validate one checkpoint (latest when ``step`` is None)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory!r}")
        return read_checkpoint_file(self.path_for(step))

    def restore_or_initialize(self, initialize: Optional[Callable] = None):
        """Auto-resume: ``(step, components)`` from the newest checkpoint
        that VALIDATES, scanning past corrupt/partial ones with a warning;
        ``(None, initialize())`` — or ``(None, None)`` — when no valid
        checkpoint exists.  This is the call a preempted job makes
        unconditionally at startup."""
        for step in reversed(self.all_steps()):
            try:
                return step, self.restore(step)
            except CheckpointCorruptError as e:
                warnings.warn(
                    f"skipping corrupt checkpoint for step {step}: {e}",
                    stacklevel=2)
            except FileNotFoundError:
                continue
        return None, (initialize() if initialize is not None else None)


# ---------------------------------------------------------------------------
# BadStepGuard
# ---------------------------------------------------------------------------


def snapshot_state(state):
    """Host copy of a device-state pytree (one sync) — the rollback
    anchor :class:`BadStepGuard` refreshes on clean steps."""
    return jax.tree_util.tree_map(
        lambda x: np.array(x) if isinstance(x, jax.Array) else x, state)


def restore_state(host_state):
    """Re-device a :func:`snapshot_state` copy."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
        host_state)


class BadStepGuard:
    """Escalation above the scaler's silent skip loop.

    ``ScalerState`` handles a *transient* overflow correctly — halve the
    scale, skip the step, move on.  What it cannot see is a *storm*: a
    diverging run overflows every step, the scale collapses to
    ``min_loss_scale``, and training silently stops making progress while
    burning pod-hours.  The guard watches consecutive skipped steps and
    after ``patience`` of them escalates through ``policy`` — one stage
    per escalation event, last stage sticky:

    * ``"warn"`` — log loudly, keep going (storms sometimes pass);
    * ``"rollback"`` — restore the last known-good snapshot (params,
      optimizer slots, step counter; the CURRENT — already-halved — loss
      scale is kept so the same storm is not immediately re-entered) and
      continue;
    * ``"raise"`` — :class:`TrainingDivergedError`; let the operator (or
      the auto-resume wrapper) decide.

    Clean-path cost: ``observe`` appends the step's on-device skip flag
    (an i32 scalar the fused step already computes) to a deque and
    consumes only flags whose buffers report ``is_ready()`` — no host
    sync, no extra dispatch (verified against ``step_cache.stats()``).
    Blocking reads happen only when the pending deque exceeds
    ``max_pending`` (default ``4 * patience``) — i.e. only under storms,
    where a sync is the least of the run's problems.

    Fused path::

        guard = BadStepGuard(patience=8, policy=("warn", "rollback",
                                                 "raise"))
        guard.attach(step)           # TrainStep notifies the guard per call
        for x, y in loader:
            loss = step(x, y)        # guard escalates as configured

    Eager step-cache path (``amp.initialize`` + ``optimizer.step()``)::

        guard.attach_optimizer(optimizer)   # observes the scaler skip flag
    """

    def __init__(self, patience: int = 5,
                 policy: Sequence[str] | str = ("warn", "rollback", "raise"),
                 snapshot_interval: int = 100,
                 max_pending: Optional[int] = None,
                 on_event: Optional[Callable] = None):
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        if isinstance(policy, str):
            policy = (policy,)
        policy = tuple(policy)
        for stage in policy:
            if stage not in ("warn", "rollback", "raise"):
                raise ValueError(f"unknown guard policy stage {stage!r}")
        if not policy:
            raise ValueError("policy must name at least one stage")
        self.patience = patience
        self.policy = policy
        self.snapshot_interval = snapshot_interval
        self.max_pending = (4 * patience if max_pending is None
                            else max_pending)
        self.on_event = on_event
        self._pending: collections.deque = collections.deque()
        self._streak = 0
        self._escalations = 0
        self._clean_since_snapshot = 0
        self._snapshot = None
        self._step = None       # attached TrainStep (fused path)
        self.stats = {"observed": 0, "skipped": 0, "escalations": 0,
                      "rollbacks": 0}

    # -- wiring ------------------------------------------------------------
    def attach(self, train_step):
        """Attach to a fused ``TrainStep`` (or any object with a mutable
        ``.state`` carrying ``scaler.overflow``): the step notifies the
        guard after each call; an initial rollback snapshot is taken now."""
        self._step = train_step
        train_step._guard = self
        if "rollback" in self.policy:
            self._snapshot = snapshot_state(train_step.state)
        return train_step

    def attach_optimizer(self, optimizer):
        """Attach to an amp-processed optimizer on the eager step-cache
        surface.  The skip flag comes for free on both eager modes: under
        ``defer_scale_update=True`` it is the deferred scaler's on-device
        overflow flag (captured BEFORE the step program donates it — no
        added sync, no added dispatch); in reference-exact mode the skip
        decision is already host-known — ``scale_loss``'s one-shot
        ``skip_step`` patch REPLACES the wrapper below for skipped calls,
        so it notifies ``stash._guard`` directly (amp/handle.py).
        Rollback needs a state snapshot the eager surface does not own,
        so the rollback stage degrades to warn here unless the caller
        layers its own snapshot management."""
        guard = self
        stash = getattr(optimizer, "_amp_stash", None)
        if stash is not None:
            stash._guard = self
        orig_step = optimizer.step

        def guarded_step(closure=None):
            flag = 0
            if stash is not None:
                deferred = getattr(stash, "_deferred_scaler", None)
                if deferred is not None:
                    flag = deferred.state.overflow
            ret = orig_step() if closure is None else orig_step(closure)
            guard.observe(flag)
            return ret

        optimizer.step = guarded_step
        return optimizer

    # -- observation -------------------------------------------------------
    def observe(self, skip_flag):
        """Record one step's skip flag (device i32 scalar, python int, or
        bool).  Device flags are consumed lazily — see class docstring."""
        self.stats["observed"] += 1
        self._pending.append(skip_flag)
        self._drain(block=False)
        while len(self._pending) > self.max_pending:
            self._consume(self._pending.popleft())

    def flush(self):
        """Consume every pending flag (blocking).  Call at loop end, or
        before trusting ``stats`` in a test."""
        self._drain(block=True)

    def _drain(self, block: bool):
        while self._pending:
            flag = self._pending[0]
            if not block:
                ready = getattr(flag, "is_ready", None)
                if ready is not None and not ready():
                    return
            self._consume(self._pending.popleft())

    def _consume(self, flag):
        skipped = bool(int(flag))
        if skipped:
            self.stats["skipped"] += 1
            self._streak += 1
            self._clean_since_snapshot = 0
            if self._streak >= self.patience:
                self._streak = 0
                self._escalate()
        else:
            self._streak = 0
            self._clean_since_snapshot += 1
            if (self._step is not None and "rollback" in self.policy
                    and self._clean_since_snapshot
                    >= self.snapshot_interval):
                self._refresh_snapshot()

    def _refresh_snapshot(self):
        # the pending deque is empty here (we are inside a drain), so the
        # current state is at least as new as every observed flag;
        # snapshotting it can only capture MORE confirmed-clean steps
        self._snapshot = snapshot_state(self._step.state)
        self._clean_since_snapshot = 0

    # -- escalation --------------------------------------------------------
    def _escalate(self):
        stage = self.policy[min(self._escalations, len(self.policy) - 1)]
        self._escalations += 1
        self.stats["escalations"] += 1
        event = {"stage": stage, "escalation": self._escalations,
                 "patience": self.patience}
        if self.on_event is not None:
            self.on_event(event)
        msg = (f"BadStepGuard: {self.patience} consecutive overflow-skipped "
               f"steps (escalation #{self._escalations}, stage {stage!r})")
        if stage == "raise":
            raise TrainingDivergedError(
                msg + " — loss scale has collapsed; training is diverging")
        warnings.warn(msg, stacklevel=3)
        if stage == "rollback":
            self._rollback()

    def _rollback(self):
        if self._step is None or self._snapshot is None:
            warnings.warn(
                "BadStepGuard: rollback requested but no snapshot is "
                "available (eager surface, or attach() not called) — "
                "degrading to warn", stacklevel=4)
            return
        restored = restore_state(self._snapshot)
        current = self._step.state
        # keep the CURRENT (post-halving) loss scale: restoring the
        # snapshot's larger scale would walk straight back into the storm
        if hasattr(restored, "scaler") and hasattr(current, "scaler"):
            restored = restored._replace(
                scaler=restored.scaler._replace(
                    loss_scale=current.scaler.loss_scale,
                    unskipped=jax.numpy.zeros((), jax.numpy.int32),
                    overflow=jax.numpy.zeros((), jax.numpy.int32)))
        self._step.state = restored
        self.stats["rollbacks"] += 1
