"""Deterministic, seedable fault-injection (chaos) harness.

The reference repo can claim "bitwise accurate" save/resume but cannot
*prove* it under failure: nothing in an eager CUDA stack can kill a save
mid-write on purpose, stall a collective, or force an overflow storm at a
chosen step.  Here every recovery path in the resilience runtime
(`apex_tpu.runtime.resilience`, `apex_tpu.parallel.distributed`) threads
through named hook points, and a :class:`ChaosController` installed for the
duration of a test decides — deterministically — what happens at each one.

Hook points currently wired (grep for ``chaos.hook(`` to enumerate):

====================  =====================================================
point                 fires
====================  =====================================================
``ckpt.mid_write``    half-way through the checkpoint payload write (tmp
                      file has partial bytes; final path untouched)
``ckpt.pre_rename``   payload fully written + fsynced, rename not yet done
``ckpt.post_rename``  checkpoint durable at its final path
``ckpt.reshard``      start of each component's cross-plan reshard during
                      elastic restore (disk already read; device
                      placement pending — a kill here must leave the
                      checkpoint loadable by the next attempt)
``ckpt.shard_write``  before each schema-3 shard file write
                      (``resilience.stream_components_to_dir``); a kill
                      here leaves a partial shard directory with NO
                      manifest — the previous checkpoint must stay the
                      newest valid one
``host.loss``         each cluster member's heartbeat tick
                      (``cluster.membership.Member.beat``); ``"kill"``
                      fells the host (it stops heartbeating and drops
                      out of the next membership epoch)
``coordinator.loss``  before each coordinator failure-detection scan
                      (``cluster.coordinator.Coordinator.scan``);
                      ``"kill"`` fells the coordinator — a successor
                      rebuilt over the same KV store must keep epochs
                      monotonic
``heartbeat.delay``   in the heartbeat path, after the liveness decision
                      is armed; a CALLABLE action's return value (or
                      ``delay_s``) skews that member's heartbeat
                      timestamp backwards — under ``miss_threshold``
                      consecutive misses this must NOT produce a new
                      membership epoch (false-positive guard)
``device.loss``       each elastic device-set detection
                      (``runtime.elastic.current_devices``); a CALLABLE
                      action's return value replaces the device set — an
                      int ``k`` keeps the first ``k`` devices, a sequence
                      becomes the set verbatim — simulating
                      preempt→shrink→regrow deterministically on the
                      8-virtual-CPU-device mesh
``dist.init``         before each ``jax.distributed.initialize`` attempt
``dist.collective``   inside ``timed_flat_dist_call``'s worker thread
``train.step``        before each fused ``TrainStep.__call__`` dispatch
``amp.backward``      at ``scale_loss`` exit on the eager amp surface,
                      before gradients are unscaled
====================  =====================================================

Serve-fleet hook points (the elastic serving failure surface;
docs/resilience.md carries the failure-mode table):

==========================  ===============================================
point                       fires
==========================  ===============================================
``serve.kv_handoff``        before each KV block file of a streamed
                            handoff or session snapshot
                            (``resilience.stream_kv_handoff``); a kill
                            leaves a manifest-less shard directory the
                            adopter must reject, a fail is a recoverable
                            stream fault (the disagg coordinator discards
                            and re-streams once)
``serve.session_snapshot``  before each live-session KV snapshot the
                            serve fleet writes
                            (``serve.elastic.ServeFleet``); a kill fells
                            the snapshotting replica mid-cycle (its
                            debris must be rejected, the previous
                            committed snapshot stands), a fail skips this
                            round cleanly
``serve.migrate``           before each restore of a lost session into a
                            survivor's pool; a kill fells the ADOPTING
                            replica (the snapshot stays on shared storage
                            for the next epoch), a fail abandons the
                            restore cleanly — the session falls back to
                            the recompute re-prefill path
==========================  ===============================================

Actions: ``"kill"`` raises :class:`ChaosKilled` (a simulated preemption —
deliberately NOT a subclass of ``Exception``-wrapping framework errors, so
recovery code that catches "expected" failures still dies to it the way a
real SIGKILL would end the process); ``"fail"`` raises
:class:`ChaosInjectedFailure` (or a caller-supplied exception) — the
recoverable-error case retry loops must absorb; ``"delay"`` sleeps, for
timeout paths; ``"nonfinite_grads"`` is returned to the hook's caller,
which interprets it (the fused train step taints the batch so every
gradient goes non-finite).  A callable action is invoked with the hook
context and its return value handed back.

Zero cost when idle: every hook site guards on :func:`active`, one global
``is None`` check, so production steps pay nothing.

Usage (tests)::

    from apex_tpu.runtime import chaos

    with chaos.session(seed=0) as c:
        c.on("ckpt.mid_write", action="kill")          # next save dies mid-write
        with pytest.raises(chaos.ChaosKilled):
            manager.save(step=5, model=model.state_dict())
    # controller uninstalled; c.log records every firing for assertions
"""
from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Callable, Optional

_ACTIONS = ("kill", "fail", "delay", "nonfinite_grads")


class ChaosError(RuntimeError):
    """Base class for injected faults."""


class ChaosKilled(ChaosError):
    """Simulated preemption/SIGKILL at a hook point.  Recovery code must
    treat this as process death: never catch it to continue the operation
    that was killed."""


class ChaosInjectedFailure(ChaosError):
    """Injected *recoverable* failure (a flaky peer, a full disk): the
    error retry/backoff paths are expected to absorb this one."""


class _Fault:
    __slots__ = ("point", "action", "at", "after", "times", "delay_s",
                 "probability", "exc")

    def __init__(self, point, action, at, after, times, delay_s,
                 probability, exc):
        if not (callable(action) or action in _ACTIONS):
            raise ValueError(f"unknown chaos action {action!r}; "
                             f"expected one of {_ACTIONS} or a callable")
        self.point = point
        self.action = action
        self.at = frozenset(at) if at is not None else None
        self.after = after
        self.times = times
        self.delay_s = delay_s
        self.probability = probability
        self.exc = exc

    def matches(self, count, rng):
        if self.times == 0:
            return False
        if self.at is not None:
            if count not in self.at:
                return False
        elif count < self.after:
            return False
        if self.probability < 1.0 and rng.random() >= self.probability:
            return False
        return True


class ChaosController:
    """Deterministic fault scheduler.

    ``seed`` drives the single ``random.Random`` consulted for
    probabilistic faults; with the default ``probability=1.0`` no
    randomness is consumed at all, so runs are reproducible by
    construction.  Each hook point keeps its own 0-based call counter
    (``counts``); faults select on it via ``at=`` (explicit indices) or
    ``after=`` (threshold).
    """

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._faults: list[_Fault] = []
        #: per-point hook-call counters (0-based index of the NEXT call)
        self.counts: dict[str, int] = {}
        #: every firing, as (point, call_index, action) — assert on this
        self.log: list[tuple] = []

    def on(self, point: str, action="kill", *, at=None, after: int = 0,
           times: Optional[int] = None, delay_s: float = 0.0,
           probability: float = 1.0,
           exc: Optional[BaseException] = None) -> "ChaosController":
        """Arm ``action`` at hook ``point``.

        ``at``: iterable of call indices (0-based, per point) to fire on;
        ``after``: fire on every call from this index (when ``at`` is None);
        ``times``: total firings before the fault disarms (-1 = unlimited;
        default: one per ``at`` index, else 1);
        ``delay_s``: sleep length for ``action="delay"``;
        ``probability``: per-eligible-call firing probability (seeded);
        ``exc``: exception instance for ``action="fail"``.
        Returns self for chaining.
        """
        if isinstance(at, int):
            at = (at,)
        if times is None:
            times = len(at) if at is not None else 1
        with self._lock:
            self._faults.append(_Fault(point, action, at, after, times,
                                       delay_s, probability, exc))
        return self

    def fire(self, point: str, **ctx):
        """Advance ``point``'s counter and run the first matching fault.
        Returns the action result (a string like ``"nonfinite_grads"``, a
        callable's return value, or None when nothing fired)."""
        with self._lock:
            count = self.counts.get(point, 0)
            self.counts[point] = count + 1
            fault = None
            for f in self._faults:
                if f.point == point and f.matches(count, self._rng):
                    if f.times > 0:
                        f.times -= 1
                    fault = f
                    break
            if fault is None:
                return None
            action_name = (fault.action if not callable(fault.action)
                           else getattr(fault.action, "__name__",
                                        "callable"))
            self.log.append((point, count, action_name))
        # mirror the receipt into the observe registry (outside the lock:
        # a JSONL sink may do IO) so chaos injections land in the same
        # event stream as the telemetry they perturb
        from ..observe import registry as _obs
        _obs.event("chaos.inject", point=point, call=count,
                   action=action_name)
        if callable(fault.action):
            return fault.action(dict(ctx, point=point, call=count))
        if fault.action == "delay":
            time.sleep(fault.delay_s)
            return "delay"
        if fault.action == "kill":
            raise ChaosKilled(f"chaos: killed at {point!r} (call {count})")
        if fault.action == "fail":
            if fault.exc is not None:
                raise fault.exc
            raise ChaosInjectedFailure(
                f"chaos: injected failure at {point!r} (call {count})")
        return fault.action  # "nonfinite_grads" et al: caller interprets

    # -- installation ------------------------------------------------------
    def __enter__(self):
        install(self)
        return self

    def __exit__(self, *exc):
        uninstall(self)
        return False


_controller: Optional[ChaosController] = None


def active() -> bool:
    """True when a controller is installed — THE guard every hook site
    checks first, so idle cost is one global read."""
    return _controller is not None


def install(controller: ChaosController):
    global _controller
    if _controller is not None:
        raise RuntimeError("a ChaosController is already installed")
    _controller = controller


def uninstall(controller: Optional[ChaosController] = None):
    global _controller
    if controller is not None and _controller is not controller:
        return
    _controller = None


def hook(point: str, **ctx):
    """Fire hook ``point`` on the installed controller (no-op when none)."""
    c = _controller
    if c is None:
        return None
    return c.fire(point, **ctx)


@contextlib.contextmanager
def session(seed: int = 0):
    """``with chaos.session(seed=0) as c: c.on(...)`` — install a fresh
    controller for the scope, uninstall on exit (exception-safe)."""
    c = ChaosController(seed=seed)
    install(c)
    try:
        yield c
    finally:
        uninstall(c)
