"""apex_tpu.runtime — native host runtime (C++ data plane).

The reference keeps its host-side data plane in C++ (`apex_C`
flatten/unflatten, csrc/flatten_unflatten.cpp; the examples' side-stream
prefetcher byte-work, examples/imagenet/main_amp.py:264-302).  This package
is the TPU-native equivalent: a small C++ library (csrc/runtime.cpp) built
on first use with the system toolchain and bound over ctypes — no torch, no
pybind11.  Degrades to numpy fallbacks when no compiler is present,
mirroring the reference's Python-only install path (setup.py extensions
optional, README.md:130-139).

Public surface:
  flatten(arrays) / unflatten(flat, like)   — bucket coalescing (apex_C)
  normalize_u8_nhwc_to_f32_nchw(...)        — fused decode-side normalize
  f32_to_bf16(x)                            — bulk host cast (RNE)
  available()                               — True when the native lib loads
  DataPrefetcher                            — apex_tpu.runtime.data
  step_cache                                — compiled step-program cache
                                              (apex_tpu.runtime.step_cache)
  executor                                  — the one dispatch choke point:
                                              Program descriptors, donation
                                              policy, overlap knobs
                                              (apex_tpu.runtime.executor)
  resilience                                — atomic/async CheckpointManager,
                                              auto-resume, BadStepGuard
                                              (apex_tpu.runtime.resilience)
  chaos                                     — deterministic fault injection
                                              (apex_tpu.runtime.chaos)
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc", "runtime.cpp")
_lock = threading.Lock()
_lib = None


def _build_and_load():
    """Compile csrc/runtime.cpp into a cached .so and dlopen it."""
    cache = os.environ.get("APEX_TPU_CACHE",
                           os.path.join(tempfile.gettempdir(),
                                        "apex_tpu_runtime"))
    os.makedirs(cache, exist_ok=True)
    try:
        src_mtime = int(os.path.getmtime(_SRC))
    except OSError:
        return None
    so = os.path.join(cache, f"libapex_runtime_{src_mtime}.so")
    if not os.path.exists(so):
        tmp = so + f".build{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
               _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            return None
        os.replace(tmp, so)  # atomic vs concurrent builders
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def _get():
    global _lib
    if _lib is None:
        with _lock:
            if _lib is None:
                lib = _build_and_load()
                if lib is not None:
                    lib.apex_flatten.argtypes = [
                        ctypes.POINTER(ctypes.c_void_p),
                        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                        ctypes.c_void_p, ctypes.c_int]
                    lib.apex_unflatten.argtypes = [
                        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                        ctypes.c_int]
                    for nrm in ("apex_normalize_u8_nhwc_to_f32_nchw",
                                "apex_normalize_u8_nhwc_to_f32_nhwc"):
                        getattr(lib, nrm).argtypes = [
                            ctypes.c_void_p, ctypes.c_void_p,
                            ctypes.c_int64, ctypes.c_int64,
                            ctypes.c_int64, ctypes.c_int64,
                            ctypes.POINTER(ctypes.c_float),
                            ctypes.POINTER(ctypes.c_float), ctypes.c_int]
                    lib.apex_f32_to_bf16.argtypes = [
                        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                        ctypes.c_int]
                _lib = lib if lib is not None else False
    return _lib or None


def available() -> bool:
    """True when the native runtime library is (or can be) loaded."""
    return _get() is not None


def _as_contig(a):
    return np.ascontiguousarray(a)


def flatten(arrays, out=None, threads: int = 0):
    """Coalesce a list of same-dtype ndarrays into one flat 1-d array
    (apex_C.flatten, csrc/flatten_unflatten.cpp:5-8)."""
    arrays = [_as_contig(np.asarray(a)) for a in arrays]
    if not arrays:
        return np.empty((0,), np.float32)
    dtype = arrays[0].dtype
    if any(a.dtype != dtype for a in arrays):
        raise TypeError(
            "flatten: all arrays must share a dtype (bucket per dtype, "
            "reference split_half_float_double)")
    total = sum(a.size for a in arrays)
    if out is None:
        out = np.empty((total,), dtype)
    elif out.size != total or out.dtype != dtype:
        raise ValueError("flatten: bad out buffer")
    elif not out.flags["C_CONTIGUOUS"]:
        raise ValueError("flatten: out buffer must be C-contiguous")
    lib = _get()
    if lib is None:
        off = 0
        for a in arrays:
            out[off:off + a.size] = a.ravel()
            off += a.size
        return out
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    nbytes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    lib.apex_flatten(srcs, nbytes, n, out.ctypes.data, threads)
    return out


def unflatten(flat, like, threads: int = 0):
    """Split a flat array back into tensors shaped like ``like``
    (apex_C.unflatten, csrc/flatten_unflatten.cpp:10-13)."""
    flat = _as_contig(np.asarray(flat))
    outs = [np.empty(np.shape(t), flat.dtype) for t in like]
    total = sum(o.size for o in outs)
    if flat.size != total:
        raise ValueError(
            f"unflatten: flat has {flat.size} elements, targets need {total}")
    lib = _get()
    if lib is None:
        off = 0
        for o in outs:
            o[...] = flat[off:off + o.size].reshape(o.shape)
            off += o.size
        return outs
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
    nbytes = (ctypes.c_int64 * n)(*[o.nbytes for o in outs])
    lib.apex_unflatten(flat.ctypes.data, dsts, nbytes, n, threads)
    return outs


def normalize_u8_nhwc_to_f32_nchw(batch, mean, std, threads: int = 0):
    """uint8 (N,H,W,C) → float32 (N,C,H,W), (x/255 - mean)/std fused — the
    prefetcher's per-batch byte work (main_amp.py:287-301) natively."""
    batch = _as_contig(np.asarray(batch, np.uint8))
    n, h, w, c = batch.shape
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    if mean.shape != (c,) or std.shape != (c,):
        raise ValueError(f"mean/std must have shape ({c},)")
    lib = _get()
    if lib is None:
        x = batch.astype(np.float32) / 255.0
        x = (x - mean) / std
        return np.ascontiguousarray(x.transpose(0, 3, 1, 2))
    out = np.empty((n, c, h, w), np.float32)
    lib.apex_normalize_u8_nhwc_to_f32_nchw(
        batch.ctypes.data, out.ctypes.data, n, h, w, c,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), threads)
    return out


def normalize_u8_nhwc_to_f32_nhwc(batch, mean, std, threads: int = 0):
    """uint8 (N,H,W,C) → float32 (N,H,W,C), (x/255 - mean)/std fused,
    layout-preserving — the input path for channels-last models
    (nn.to_channels_last): the decode layout IS the compute layout, so
    the transpose disappears from the pipeline entirely."""
    batch = _as_contig(np.asarray(batch, np.uint8))
    n, h, w, c = batch.shape
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    if mean.shape != (c,) or std.shape != (c,):
        raise ValueError(f"mean/std must have shape ({c},)")
    lib = _get()
    if lib is None:
        x = batch.astype(np.float32) / 255.0
        return np.ascontiguousarray((x - mean) / std)
    out = np.empty((n, h, w, c), np.float32)
    lib.apex_normalize_u8_nhwc_to_f32_nhwc(
        batch.ctypes.data, out.ctypes.data, n, h, w, c,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), threads)
    return out


def f32_to_bf16(x, threads: int = 0):
    """Bulk float32 → bfloat16 (round-to-nearest-even) on host."""
    import ml_dtypes
    x = _as_contig(np.asarray(x, np.float32))
    lib = _get()
    if lib is None:
        return x.astype(ml_dtypes.bfloat16)
    out = np.empty(x.shape, np.uint16)
    lib.apex_f32_to_bf16(x.ctypes.data, out.ctypes.data, x.size, threads)
    return out.view(ml_dtypes.bfloat16)


from .data import DataPrefetcher  # noqa: E402,F401
from . import step_cache  # noqa: E402,F401
from . import executor  # noqa: E402,F401
from .executor import (  # noqa: E402,F401
    Executor, Program, set_overlap, overlap_enabled)
from . import chaos  # noqa: E402,F401
from . import resilience  # noqa: E402,F401
from .resilience import (  # noqa: E402,F401
    BadStepGuard, CheckpointCorruptError, CheckpointManager,
    CheckpointReshardError, SaveHandle, TrainingDivergedError)
from . import elastic  # noqa: E402,F401
from .elastic import (  # noqa: E402,F401
    ElasticTrainer, current_devices, elastic_restore)

__all__ = ["flatten", "unflatten", "normalize_u8_nhwc_to_f32_nchw",
           "normalize_u8_nhwc_to_f32_nhwc", "f32_to_bf16", "available",
           "DataPrefetcher", "step_cache", "executor", "Executor",
           "Program", "set_overlap", "overlap_enabled", "chaos",
           "resilience",
           "CheckpointManager", "CheckpointCorruptError", "SaveHandle",
           "BadStepGuard", "TrainingDivergedError", "elastic",
           "CheckpointReshardError", "ElasticTrainer", "elastic_restore",
           "current_devices"]
