"""Eager-looking autograd over JAX: the piece that lets Apex's imperative
training-loop API (``out = model(x); loss = crit(out, y);
scaled_loss.backward(); optimizer.step()``) run on a trace-once functional
runtime.

How it works, TPU-first rather than torch-tape-faithful:

* ``model(x)`` and tape-aware ops return :class:`Tensor` — a concrete jnp
  value (usable immediately: print it, branch on it) plus a record of the op
  and its inputs.
* ``loss.backward()`` **linearizes** the recorded graph into a hashable
  program (topologically ordered instruction tuple).  Equal programs across
  training steps hit a cache of compiled ``jax.value_and_grad`` executables,
  so the steady-state cost of the imperative API is one compiled XLA program
  per backward — the Python-side graph build is a few microseconds per op.
* gradients accumulate into ``Parameter.grad`` (torch semantics, which amp's
  grad-accumulation path relies on — reference
  apex/amp/_process_optimizer.py:142-158).

Randomness (dropout) is recorded as a const leaf so the backward re-execution
sees the identical mask.  BatchNorm running stats update eagerly on the
forward call and are *not* re-updated by backward's re-execution.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .amp import policy as _policy
from .nn.parameter import Parameter

Array = jax.Array

_grad_enabled = [True]


@contextlib.contextmanager
def no_grad():
    _grad_enabled.append(False)
    try:
        yield
    finally:
        _grad_enabled.pop()


def is_grad_enabled() -> bool:
    return _grad_enabled[-1]


# ---------------------------------------------------------------------------
# Op registry: name -> callable on raw arrays
# ---------------------------------------------------------------------------

_OPS: Dict[str, Any] = {}


def register_op(name: str, fn):
    _OPS[name] = fn
    return fn


def _init_builtin_ops():
    _OPS.update({
        "add": lambda a, b: a + b,
        "sub": lambda a, b: a - b,
        "rsub": lambda a, b: b - a,
        "mul": lambda a, b: a * b,
        "div": lambda a, b: a / b,
        "rdiv": lambda a, b: b / a,
        "pow": lambda a, b: a ** b,
        "neg": lambda a: -a,
        "abs": jnp.abs,
        "exp": jnp.exp,
        "log": jnp.log,
        "sqrt": jnp.sqrt,
        "matmul": lambda a, b: jnp.matmul(
            a, b, preferred_element_type=jnp.float32).astype(
                jnp.result_type(a, b)),
        "sum": lambda a, axis=None, keepdims=False: jnp.sum(
            a, axis=axis, keepdims=keepdims),
        "mean": lambda a, axis=None, keepdims=False: jnp.mean(
            a, axis=axis, keepdims=keepdims),
        "max": lambda a, axis=None, keepdims=False: jnp.max(
            a, axis=axis, keepdims=keepdims),
        "min": lambda a, axis=None, keepdims=False: jnp.min(
            a, axis=axis, keepdims=keepdims),
        "reshape": lambda a, shape=None: a.reshape(shape),
        "transpose": lambda a, axes=None: jnp.transpose(a, axes),
        "getitem": lambda a, idx=None: a[idx],
        "getitem_dyn": _getitem_dyn,
        "astype": lambda a, dtype=None: a.astype(dtype),
        "squeeze": lambda a, axis=None: jnp.squeeze(a, axis),
    })


_DYN_SLOT = "__dyn_index__"


def _getitem_dyn(a, *index_arrays, structure=None):
    """Rebuild an index tuple whose array elements were lifted as tape
    inputs (marked by _DYN_SLOT placeholders in ``structure``)."""
    it = iter(index_arrays)
    idx = tuple(next(it) if e == _DYN_SLOT else _thaw(e) for e in structure)
    return a[idx if len(idx) != 1 else idx[0]]


_init_builtin_ops()


def _is_arraylike(x) -> bool:
    return isinstance(x, (jax.Array, jnp.ndarray)) or (
        hasattr(x, "shape") and hasattr(x, "dtype")
        and not isinstance(x, (Tensor, Parameter)))


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

class Tensor:
    """A concrete value + its provenance on the tape."""
    __slots__ = ("value", "op", "inputs", "static", "module", "m_training",
                 "m_key", "pol")

    def __init__(self, value, op, inputs=(), static=(), module=None,
                 m_training=False, m_key=None):
        self.value = value
        self.op = op                    # "const" | "param" | "module" | op name
        self.inputs = tuple(inputs)     # Tensors (for const/param: source)
        self.static = static            # hashable static arg descriptor
        self.module = module
        self.m_training = m_training
        self.m_key = m_key
        self.pol = _policy.current_policy()

    # -- numpy-ish surface -------------------------------------------------
    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    def item(self):
        return self.value.item()

    def __float__(self):
        return float(self.value)

    def __array__(self, dtype=None):
        import numpy as np
        return np.asarray(self.value, dtype)

    def numpy(self):
        import numpy as np
        return np.asarray(self.value)

    def detach(self):
        return Tensor(self.value, "const")

    def __repr__(self):
        return f"tape.Tensor({self.value!r})"

    # -- graph building ----------------------------------------------------
    def _binop(self, other, name):
        return record_op(name, (self, other), {})

    __add__ = lambda self, o: self._binop(o, "add")
    __radd__ = lambda self, o: self._binop(o, "add")
    __sub__ = lambda self, o: self._binop(o, "sub")
    __rsub__ = lambda self, o: self._binop(o, "rsub")
    __mul__ = lambda self, o: self._binop(o, "mul")
    __rmul__ = lambda self, o: self._binop(o, "mul")
    __truediv__ = lambda self, o: self._binop(o, "div")
    __rtruediv__ = lambda self, o: self._binop(o, "rdiv")
    __pow__ = lambda self, o: self._binop(o, "pow")
    __matmul__ = lambda self, o: self._binop(o, "matmul")
    __neg__ = lambda self: record_op("neg", (self,), {})

    def sum(self, axis=None, keepdims=False):
        return record_op("sum", (self,), {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return record_op("mean", (self,), {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return record_op("max", (self,), {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return record_op("min", (self,), {"axis": axis, "keepdims": keepdims})

    def log(self):
        return record_op("log", (self,), {})

    def exp(self):
        return record_op("exp", (self,), {})

    def sqrt(self):
        return record_op("sqrt", (self,), {})

    def abs(self):
        return record_op("abs", (self,), {})

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return record_op("reshape", (self,), {"shape": shape})

    def view(self, *shape):
        return self.reshape(*shape)

    def transpose(self, *axes):
        return record_op("transpose", (self,), {"axes": axes or None})

    def squeeze(self, axis=None):
        return record_op("squeeze", (self,), {"axis": axis})

    def astype(self, dtype):
        return record_op("astype", (self,), {"dtype": jnp.dtype(dtype).name})

    def float(self):
        return self.astype(jnp.float32)

    def half(self):
        return self.astype(jnp.float16)

    def __getitem__(self, idx):
        elems = idx if isinstance(idx, tuple) else (idx,)
        if any(isinstance(e, Tensor) or _is_arraylike(e) for e in elems):
            # array indices (gathers, boolean masks) are tape inputs, not
            # static constants — they change between steps and are unhashable
            arrays = [e for e in elems
                      if isinstance(e, Tensor) or _is_arraylike(e)]
            structure = tuple(
                _DYN_SLOT if (isinstance(e, Tensor) or _is_arraylike(e))
                else _freeze(e) for e in elems)
            return record_op("getitem_dyn", (self, *arrays),
                             {"structure": structure})
        return record_op("getitem", (self,), {"idx": idx})

    def __len__(self):
        return len(self.value)

    def __iter__(self):
        # element unpacking via per-element getitem records: tuple-valued
        # module outputs (e.g. RNN (output, hiddens)) yield elements,
        # array values yield rows (the pre-__iter__ sequence-protocol
        # behavior, which defining __iter__ would otherwise disable)
        return (self[i] for i in range(len(self.value)))

    # -- autograd ----------------------------------------------------------
    def backward(self):
        backward(self)


def lift(x) -> Tensor:
    """Wrap a raw value / Parameter as a tape leaf."""
    if isinstance(x, Tensor):
        return x
    if isinstance(x, Parameter):
        return Tensor(x.data, "param", static=(), module=x)
    if x is None:
        # optional array-slot left empty (e.g. attention_mask=None passed
        # positionally); replays as a literal None, takes no gradient
        return Tensor(None, "none")
    return Tensor(jnp.asarray(x), "const")


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------

def _freeze(v):
    """Make a static kwarg hashable."""
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, slice):
        return ("__slice__", v.start, v.stop, v.step)
    if isinstance(v, tuple):
        return tuple(_freeze(x) for x in v)
    return v


def _thaw(v):
    if isinstance(v, tuple):
        if len(v) == 4 and v[0] == "__slice__":
            return slice(v[1], v[2], v[3])
        return tuple(_thaw(x) for x in v)
    return v


def record_op(name: str, array_args: Sequence, static_kwargs: Dict) -> Tensor:
    """Record ``name(*array_args, **static_kwargs)``; array_args may mix
    Tensors, Parameters and raw arrays/scalars."""
    fn = _OPS[name]
    inputs = tuple(lift(a) for a in array_args)
    static = tuple(sorted(
        (k, _freeze(v)) for k, v in static_kwargs.items()))
    kwargs = {k: _thaw(v) for k, v in static}
    args, kwargs2 = _policy.apply_op_policy(
        name, tuple(t.value for t in inputs), kwargs)
    value = fn(*args, **kwargs2)
    if not is_grad_enabled():
        return Tensor(value, "const")
    return Tensor(value, name, inputs, static)


def _amp_tags(module):
    """amp.initialize tags models with cast dtypes / an O1 policy
    (apex_tpu/amp/_initialize.py) — the tape-level equivalent of the
    reference's model.forward patch (_initialize.py:190-201).  Untagged
    modules (criterions, user modules) fall back to the session's ambient O1
    policy, mirroring the reference's global torch patching."""
    from .amp._amp_state import _amp_state
    in_cast = getattr(module, "_amp_input_cast_dtype", None)
    out_cast = getattr(module, "_amp_output_cast_dtype", None)
    pol = getattr(module, "_amp_policy", None)
    if pol is None and in_cast is None:
        pol = _amp_state.ambient_policy
    # an explicit disable_casts scope beats both the module tag and the
    # ambient fallback (reference: handle inactive -> wrappers passthrough);
    # O2's input/output dtype casts are part of the patched forward and stay
    if pol is not None and _policy.casts_disabled():
        pol = None
    return in_cast, out_cast, pol


def _run_module(module, ctx, in_vals, in_cast, out_cast, pol, static=()):
    if in_cast is not None:
        in_vals = tuple(
            v.astype(in_cast) if hasattr(v, "dtype")
            and jnp.issubdtype(v.dtype, jnp.floating) else v
            for v in in_vals)
    kwargs = {k: _thaw(v) for k, v in static}
    scope = _policy.autocast(pol) if pol is not None \
        else contextlib.nullcontext()
    with scope:
        value = module.forward(ctx, *in_vals, **kwargs)
    if out_cast is not None and hasattr(value, "dtype") and \
            jnp.issubdtype(value.dtype, jnp.floating):
        value = value.astype(out_cast)
    return value


def record_module_call(module, inputs: Sequence, kwargs=None):
    """Module.__call__ entry: run eagerly (stats update now), record for
    backward re-execution.  kwargs are static (non-array) forward options
    — e.g. RNN collect_hidden/reverse — and become part of the program
    cache key."""
    from .nn.modules import Ctx
    needs_key = any(getattr(m, "p", None) is not None
                    and type(m).__name__ == "Dropout"
                    for m in module.modules()) and module.training
    key = None
    if needs_key:
        from .nn.modules import _next_key
        key = _next_key()
    for k, v in (kwargs or {}).items():
        if isinstance(v, (Tensor, Parameter)) or _is_arraylike(v):
            raise TypeError(
                f"module kwarg {k!r} is array-valued; forward kwargs are "
                "static (hashed into the program cache key) — pass arrays "
                "positionally")
    static = tuple(sorted(
        (k, _freeze(v)) for k, v in (kwargs or {}).items()))
    in_cast, out_cast, pol = _amp_tags(module)
    in_tensors = tuple(lift(x) for x in inputs)
    ctx = Ctx(env={}, stats_out=None, training=module.training, key=key)
    value = _run_module(module, ctx, tuple(t.value for t in in_tensors),
                        in_cast, out_cast, pol, static)
    if not is_grad_enabled():
        return Tensor(value, "const") if not isinstance(value, tuple) else value
    t = Tensor(value, "module", in_tensors, static=static, module=module,
               m_training=module.training, m_key=key)
    t.pol = pol
    return t


# ---------------------------------------------------------------------------
# Linearization + compiled backward
# ---------------------------------------------------------------------------

class _Program:
    """Hashable linearized graph + the live objects needed to execute it."""
    __slots__ = ("instructions", "modules", "consts", "params", "key_consts",
                 "cache_key")

    def __init__(self, instructions, modules, consts, params, key_consts,
                 cache_key):
        self.instructions = instructions
        self.modules = modules
        self.consts = consts
        self.params = params
        self.key_consts = key_consts
        self.cache_key = cache_key


def _linearize(root: Tensor) -> _Program:
    index: Dict[int, int] = {}
    instructions: List[tuple] = []
    modules: List = []
    consts: List[Array] = []
    params: List[Parameter] = []
    param_idx: Dict[int, int] = {}
    key_consts: List = []

    def visit(t: Tensor) -> int:
        if id(t) in index:
            return index[id(t)]
        if t.op == "none":
            instructions.append(("none",))
        elif t.op == "const":
            instructions.append(("const", len(consts)))
            consts.append(t.value)
        elif t.op == "param":
            p = t.module  # Parameter stashed in .module slot
            if id(p) not in param_idx:
                param_idx[id(p)] = len(params)
                params.append(p)
            instructions.append(("param", param_idx[id(p)]))
        elif t.op == "module":
            in_idx = tuple(visit(i) for i in t.inputs)
            mod = t.module
            m_params = [p for p in mod.parameters() if p is not None]
            for p in m_params:
                if id(p) not in param_idx:
                    param_idx[id(p)] = len(params)
                    params.append(p)
            p_idx = tuple(param_idx[id(p)] for p in m_params)
            key_id = None
            if t.m_key is not None:
                key_id = len(key_consts)
                key_consts.append(t.m_key)
            in_cast, out_cast, _ = _amp_tags(mod)
            instructions.append(
                ("module", len(modules), in_idx, p_idx, t.m_training, key_id,
                 jnp.dtype(in_cast).name if in_cast is not None else None,
                 jnp.dtype(out_cast).name if out_cast is not None else None,
                 t.static))
            modules.append((mod, t.pol))
        else:
            in_idx = tuple(visit(i) for i in t.inputs)
            instructions.append(("op", t.op, t.static, in_idx, len(modules)))
            modules.append((None, t.pol))
        index[id(t)] = len(instructions) - 1
        return index[id(t)]

    visit(root)
    cache_key = (
        tuple(instructions),
        tuple((id(m) if m is not None else 0,
               id(p) if p is not None else 0) for m, p in modules),
        tuple((v.shape, str(v.dtype)) for v in consts),
        tuple((p.shape, str(p.dtype)) for p in params),
    )
    return _Program(tuple(instructions), modules, consts, params, key_consts,
                    cache_key)


def _execute(program: _Program, param_vals, const_vals, key_vals):
    """Pure re-execution of the program (used under value_and_grad)."""
    from .nn.modules import Ctx
    results: List[Any] = []
    for ins in program.instructions:
        kind = ins[0]
        if kind == "none":
            results.append(None)
        elif kind == "const":
            results.append(const_vals[ins[1]])
        elif kind == "param":
            results.append(param_vals[ins[1]])
        elif kind == "module":
            (_, mod_i, in_idx, p_idx, training, key_id, in_cast, out_cast,
             static) = ins
            mod, pol = program.modules[mod_i]
            env = {id(program.params[pi]): param_vals[pi] for pi in p_idx}
            key = key_vals[key_id] if key_id is not None else None
            ctx = Ctx(env=env, stats_out={}, training=training, key=key)
            results.append(_run_module(
                mod, ctx, tuple(results[i] for i in in_idx),
                jnp.dtype(in_cast) if in_cast else None,
                jnp.dtype(out_cast) if out_cast else None, pol, static))
        else:
            _, op_name, static, in_idx, mod_i = ins
            _, pol = program.modules[mod_i]
            kwargs = {k: _thaw(v) for k, v in static}
            args = tuple(results[i] for i in in_idx)
            # re-apply the policy recorded at forward time so backward's
            # re-execution sees identical dtypes
            if pol is not None and pol.enabled:
                args, kwargs = pol.cast_args(op_name, args, kwargs)
            results.append(_OPS[op_name](*args, **kwargs))
    return results[-1]


# LRU-bounded: each cached executable closes over its _Program (pinning the
# module/param objects it references), so eviction is what lets dead models
# be collected in long-lived processes.
from collections import OrderedDict  # noqa: E402

_compiled_cache: "OrderedDict[Any, Any]" = OrderedDict()
_COMPILED_CACHE_MAX = 64


def backward(root: Tensor):
    """Compute d(root)/d(params) and accumulate into ``.grad``.

    Accumulation is part of the one compiled program: the existing
    ``.grad`` arrays enter as (donated, where the backend supports
    aliasing) inputs and the executable returns ``prev + new`` directly —
    a K-microbatch gradient-accumulation loop
    (``amp.scale_loss(..., delay_unscale=True)``) therefore costs K
    backward dispatches and nothing else: no per-parameter eager adds, no
    per-parameter dtype-cast dispatches, no extra buffers beyond the
    running sums.  (jax retraces the same jitted callable for the
    first-backward case, where every prev grad is None.)
    """
    if root.value.size != 1:
        raise RuntimeError("backward() requires a scalar loss")
    program = _linearize(root)
    if not program.params:
        raise RuntimeError("loss does not depend on any Parameter")

    cached = _compiled_cache.get(program.cache_key)
    if cached is None:
        grad_dtypes = tuple(jnp.dtype(p.dtype).name for p in program.params)

        def f(param_vals, const_vals, key_vals, prog=program):
            out = _execute(prog, param_vals, const_vals, key_vals)
            return out.astype(jnp.float32).reshape(())

        def run(param_vals, prev_grads, const_vals, key_vals):
            loss_val, grads = jax.value_and_grad(f)(param_vals, const_vals,
                                                    key_vals)
            out = []
            for g, prev, d in zip(grads, prev_grads, grad_dtypes):
                g = g.astype(d)
                out.append(g if prev is None else prev + g)
            return loss_val, out

        from .runtime.executor import donation
        cached = jax.jit(run,
                         donate_argnums=(1,) if donation.enabled else ())
        _compiled_cache[program.cache_key] = cached
        while len(_compiled_cache) > _COMPILED_CACHE_MAX:
            _compiled_cache.popitem(last=False)
    else:
        # reuse compiled executable: it closed over an older program whose
        # module/param identities match (enforced by the id-based cache_key)
        _compiled_cache.move_to_end(program.cache_key)

    prev_grads = [p.grad if p.requires_grad else None
                  for p in program.params]
    loss_val, grads = cached([p.data for p in program.params], prev_grads,
                             program.consts, program.key_consts)
    root.value = loss_val.astype(root.value.dtype)
    for p, g in zip(program.params, grads):
        if p.requires_grad:
            p.grad = g
