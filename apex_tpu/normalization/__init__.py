"""apex.normalization equivalents (reference apex/normalization/__init__.py)."""
from .fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
)
from .rms_norm import (  # noqa: F401
    FusedRMSNorm,
    fused_rms_norm,
    fused_rms_norm_affine,
)
