"""FusedRMSNorm — the RMS variant of FusedLayerNorm for the Llama-style
model families.

No reference analogue (the reference's ``fused_layer_norm_cuda``
extension implements only the mean-centered form); same design as
fused_layer_norm.py: a ``jax.custom_vjp`` whose forward saves the fp32
reciprocal-RMS residual, dispatched to the Pallas kernels
(apex_tpu/ops/pallas/rms_norm.py) on TPU with an equivalent jnp path
elsewhere (also the test oracle).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..nn.modules import Module
from ..nn.parameter import Parameter
from ..kernels.dispatch import norm_kernel_mode, pallas_mode
from ..kernels import rms_norm as _k
from .fused_layer_norm import _flatten

_f32 = jnp.float32


# -- jnp fallback path (also the test oracle) -------------------------------

def _ref_forward(x2d, weight, eps):
    xf = x2d.astype(_f32)
    ms = jnp.mean(xf * xf, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y = xf * rstd
    if weight is not None:
        y = y * weight.astype(_f32)
    return y.astype(x2d.dtype), rstd


def _ref_backward(g2d, x2d, rstd, weight):
    g = g2d.astype(_f32)
    xhat = x2d.astype(_f32) * rstd
    gh = g * weight.astype(_f32) if weight is not None else g
    c2 = jnp.mean(gh * xhat, axis=1, keepdims=True)
    dx = ((gh - xhat * c2) * rstd).astype(x2d.dtype)
    if weight is None:
        return (dx,)
    return dx, jnp.sum(g * xhat, axis=0)


def _fwd_dispatch(x2d, weight, eps):
    mode = norm_kernel_mode()
    if mode is None:
        return _ref_forward(x2d, weight, eps)
    return _k.rms_forward(x2d, weight, eps,
                          interpret=(mode == "interpret"))


def _bwd_dispatch(g2d, x2d, rstd, weight):
    mode = norm_kernel_mode()
    if mode is None:
        return _ref_backward(g2d, x2d, rstd, weight)
    return _k.rms_backward(g2d, x2d, rstd, weight,
                           interpret=(mode == "interpret"))


# -- public functional API ---------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6):
    x2d, _, n = _flatten(input, normalized_shape)
    y, _ = _fwd_dispatch(x2d, weight.reshape(n), eps)
    return y.reshape(input.shape)


def _affine_fwd(input, weight, normalized_shape, eps):
    x2d, _, n = _flatten(input, normalized_shape)
    y, rstd = _fwd_dispatch(x2d, weight.reshape(n), eps)
    return y.reshape(input.shape), (x2d, rstd, weight)


def _affine_bwd(normalized_shape, eps, res, g):
    x2d, rstd, weight = res
    n = x2d.shape[1]
    dx, dw = _bwd_dispatch(g.reshape(x2d.shape), x2d, rstd,
                           weight.reshape(n))
    return (dx.reshape(g.shape).astype(g.dtype),
            dw.reshape(weight.shape).astype(weight.dtype))


fused_rms_norm_affine.defvjp(_affine_fwd, _affine_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def fused_rms_norm(input, normalized_shape, eps=1e-6):
    x2d, _, _ = _flatten(input, normalized_shape)
    y, _ = _fwd_dispatch(x2d, None, eps)
    return y.reshape(input.shape)


def _plain_fwd(input, normalized_shape, eps):
    x2d, _, _ = _flatten(input, normalized_shape)
    y, rstd = _fwd_dispatch(x2d, None, eps)
    return y.reshape(input.shape), (x2d, rstd)


def _plain_bwd(normalized_shape, eps, res, g):
    x2d, rstd = res
    (dx,) = _bwd_dispatch(g.reshape(x2d.shape), x2d, rstd, None)
    return (dx.reshape(g.shape).astype(g.dtype),)


fused_rms_norm.defvjp(_plain_fwd, _plain_bwd)


# -- module ------------------------------------------------------------------

class FusedRMSNorm(Module):
    """Drop-in RMSNorm backed by the fused kernel; fp32 statistics for
    half inputs, matching FusedLayerNorm's contract.  Llama convention:
    eps default 1e-6, weight-only affine (no bias by construction)."""

    def __init__(self, normalized_shape, eps=1e-6, elementwise_affine=True):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine
        if elementwise_affine:
            self.weight = Parameter(jnp.ones(self.normalized_shape, _f32))
        else:
            self.register_parameter("weight", None)

    def forward(self, ctx, x):
        if self.elementwise_affine:
            return fused_rms_norm_affine(
                x, ctx.value(self.weight), self.normalized_shape, self.eps)
        return fused_rms_norm(x, self.normalized_shape, self.eps)

    def extra_repr(self):
        return (f"{self.normalized_shape}, eps={self.eps}, "
                f"elementwise_affine={self.elementwise_affine}")
