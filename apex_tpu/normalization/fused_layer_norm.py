"""FusedLayerNorm — TPU-native equivalent of the reference's
``apex.normalization.FusedLayerNorm`` (apex/normalization/fused_layer_norm.py:70,
backed by the ``fused_layer_norm_cuda`` extension, csrc/layer_norm_cuda.cpp).

The functional forms carry a ``jax.custom_vjp`` whose forward saves the fp32
``(mean, invvar)`` residuals — exactly the extension's contract
(layer_norm_cuda.cpp:133-155: fwd returns (out, mean, invvar), bwd consumes
them).  On TPU the fwd/bwd run as Pallas kernels
(apex_tpu/ops/pallas/layer_norm.py); elsewhere an equivalent jnp path is used
(the reference's CPU fallback, fused_layer_norm.py:153-161).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..nn.modules import Module
from ..nn.parameter import Parameter
from ..kernels.dispatch import norm_kernel_mode, pallas_mode
from ..kernels import layer_norm as _k

_f32 = jnp.float32


def _flatten(x, normalized_shape):
    ns = tuple(normalized_shape)
    if x.shape[x.ndim - len(ns):] != ns:
        raise ValueError(
            f"Expected input with trailing dims {ns}, got shape {x.shape} "
            "(normalized_shape must match the input's last dimensions)")
    n = 1
    for d in ns:
        n *= d
    rows = x.size // n
    return x.reshape(rows, n), rows, n


# -- jnp fallback path (also the test oracle) -------------------------------

def _ref_forward(x2d, weight, bias, eps):
    xf = x2d.astype(_f32)
    mean = jnp.mean(xf, axis=1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd
    if weight is not None:
        y = y * weight.astype(_f32) + bias.astype(_f32)
    return y.astype(x2d.dtype), mean, rstd


def _ref_backward(g2d, x2d, mean, rstd, weight):
    g = g2d.astype(_f32)
    xhat = (x2d.astype(_f32) - mean) * rstd
    gh = g * weight.astype(_f32) if weight is not None else g
    c1 = jnp.mean(gh, axis=1, keepdims=True)
    c2 = jnp.mean(gh * xhat, axis=1, keepdims=True)
    dx = ((gh - c1 - xhat * c2) * rstd).astype(x2d.dtype)
    if weight is None:
        return (dx,)
    return dx, jnp.sum(g * xhat, axis=0), jnp.sum(g, axis=0)


def _fwd_dispatch(x2d, weight, bias, eps):
    mode = norm_kernel_mode()
    if mode is None:
        return _ref_forward(x2d, weight, bias, eps)
    return _k.ln_forward(x2d, weight, bias, eps,
                         interpret=(mode == "interpret"))


def _bwd_dispatch(g2d, x2d, mean, rstd, weight):
    mode = norm_kernel_mode()
    if mode is None:
        return _ref_backward(g2d, x2d, mean, rstd, weight)
    return _k.ln_backward(g2d, x2d, mean, rstd, weight,
                          interpret=(mode == "interpret"))


# -- public functional API (reference fused_layer_norm.py:64-68) ------------

@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6):
    x2d, rows, n = _flatten(input, normalized_shape)
    y, _, _ = _fwd_dispatch(x2d, weight.reshape(n), bias.reshape(n), eps)
    return y.reshape(input.shape)


def _affine_fwd(input, weight, bias, normalized_shape, eps):
    x2d, rows, n = _flatten(input, normalized_shape)
    y, mean, rstd = _fwd_dispatch(x2d, weight.reshape(n), bias.reshape(n), eps)
    return y.reshape(input.shape), (x2d, mean, rstd, weight)


def _affine_bwd(normalized_shape, eps, res, g):
    x2d, mean, rstd, weight = res
    n = x2d.shape[1]
    dx, dw, db = _bwd_dispatch(g.reshape(x2d.shape), x2d, mean, rstd,
                               weight.reshape(n))
    return (dx.reshape(g.shape).astype(g.dtype),
            dw.reshape(weight.shape).astype(weight.dtype),
            db.reshape(weight.shape).astype(weight.dtype))


fused_layer_norm_affine.defvjp(_affine_fwd, _affine_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def fused_layer_norm(input, normalized_shape, eps=1e-6):
    x2d, _, _ = _flatten(input, normalized_shape)
    y, _, _ = _fwd_dispatch(x2d, None, None, eps)
    return y.reshape(input.shape)


def _plain_fwd(input, normalized_shape, eps):
    x2d, _, _ = _flatten(input, normalized_shape)
    y, mean, rstd = _fwd_dispatch(x2d, None, None, eps)
    return y.reshape(input.shape), (x2d, mean, rstd)


def _plain_bwd(normalized_shape, eps, res, g):
    x2d, mean, rstd = res
    (dx,) = _bwd_dispatch(g.reshape(x2d.shape), x2d, mean, rstd, None)
    return (dx.reshape(g.shape).astype(g.dtype),)


fused_layer_norm.defvjp(_plain_fwd, _plain_bwd)


# -- module (reference fused_layer_norm.py:70-166) --------------------------

class FusedLayerNorm(Module):
    """Drop-in for nn.LayerNorm backed by the fused kernel; fp32 statistics
    for half inputs (reference csrc/layer_norm_cuda.cpp:133,155)."""

    def __init__(self, normalized_shape, eps=1e-5, elementwise_affine=True):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine
        if elementwise_affine:
            self.weight = Parameter(jnp.ones(self.normalized_shape, _f32))
            self.bias = Parameter(jnp.zeros(self.normalized_shape, _f32))
        else:
            self.register_parameter("weight", None)
            self.register_parameter("bias", None)

    def forward(self, ctx, x):
        if self.elementwise_affine:
            return fused_layer_norm_affine(
                x, ctx.value(self.weight), ctx.value(self.bias),
                self.normalized_shape, self.eps)
        return fused_layer_norm(x, self.normalized_shape, self.eps)

    def extra_repr(self):
        return (f"{self.normalized_shape}, eps={self.eps}, "
                f"elementwise_affine={self.elementwise_affine}")
