"""Checkpoint save/load — the ``torch.save``/``torch.load`` role for the
three-part {model, optimizer, amp} checkpoint the reference documents
(README.md:59-99 there; amp state restore after ``amp.initialize`` with the
same opt_level for bitwise-accurate resume).

Device arrays are fetched to host numpy at save time (one sync, like
torch.save) and the container is pickled; loaders re-device through the
existing ``load_state_dict`` paths which call ``jnp.asarray``.  The
container is the schema-2 manifest format (``resilience.SCHEMA_VERSION``):
per-component checksums plus — when the components hold sharded device
arrays — the sharding layout and parallelism-plan identity that
``runtime.elastic`` reshards by on a topology change.

Resume exactness: scaler state, fp32 model weights (O2's fp32 state-dict
hook) and optimizer slots round-trip exactly; O2 *master* weights are
lazily re-derived from the fp16 model params after restore, so post-resume
trajectories can drift at fp16 rounding scale — same property as the
reference's documented O2 workflow.  For exact fp32-master checkpoints use
the legacy ``fp16_utils.FP16_Optimizer.state_dict``, which stores the
fp32 groups explicitly.
"""
from __future__ import annotations

import jax

from ..runtime.resilience import (  # noqa: F401 — re-exported surface
    CheckpointCorruptError, _to_host, read_checkpoint_file,
    write_checkpoint_file)


def save_checkpoint(path: str, **components):
    """``save_checkpoint(path, model=model.state_dict(), optimizer=
    opt.state_dict(), amp=amp.state_dict(), epoch=...)`` — any picklable
    values; jax arrays anywhere in the trees are fetched to host first.

    One write path with :class:`apex_tpu.runtime.CheckpointManager`: the
    write is atomic (tmp + fsync + rename — a preemption mid-save leaves
    the previous file intact, never a partial one) and the file carries a
    manifest (schema version + per-component checksums) that
    :func:`load_checkpoint` validates."""
    write_checkpoint_file(path, dict(components))


def load_checkpoint(path: str) -> dict:
    """Load a checkpoint written by :func:`save_checkpoint`.  Arrays come
    back as host numpy; feed the sub-dicts to the matching
    ``load_state_dict`` (model / optimizer / amp), which re-device them.

    The manifest is validated before anything is unpickled —
    :class:`~apex_tpu.runtime.resilience.CheckpointCorruptError` on
    checksum/schema mismatch; pre-manifest legacy pickles still load,
    with a warning."""
    return read_checkpoint_file(path)


def save_train_state(path: str, step) -> None:
    """Checkpoint a fused step's FULL device state (masters, half model
    copies, optimizer slots, scaler, buffers, step counter) via orbax —
    the TPU-native path for the fused-step workflow, complementing the
    pickle checkpoint above (which serves the torch-style
    model/optimizer/amp state_dict workflow).

    Works for :class:`~apex_tpu.training.TrainStep` and
    :class:`~apex_tpu.parallel.ZeroTrainStep` alike: orbax records each
    array with its sharding layout, so a ZeRO state writes per-shard and
    restores SHARDED — no gather on save, no re-scatter on load.  Resume
    is exact: unlike the state_dict path (O2 masters lazily re-derived
    from fp16), the fp32 masters round-trip bit-for-bit.

    Atomicity (same contract as :func:`save_checkpoint`): the write lands
    in a sibling tmp directory and is renamed over ``path`` only once
    fully durable, so a preemption mid-save leaves the previous
    checkpoint directory readable instead of a half-written tree.
    """
    import os
    import shutil

    import orbax.checkpoint as ocp

    final = os.path.abspath(path)
    tmp = f"{final}.tmp.{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    ckptr = ocp.StandardCheckpointer()
    # force=True: periodic checkpointing to one path (the normal loop
    # pattern) overwrites instead of raising 'Destination already exists'
    ckptr.save(tmp, step.state, force=True)
    ckptr.wait_until_finished()
    old = None
    if os.path.exists(final):
        # rename-aside + rename-in: never a moment where `final` is a
        # partial tree (os.rename cannot replace a non-empty directory)
        old = f"{final}.old.{os.getpid()}"
        os.rename(final, old)
    os.rename(tmp, final)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


class AsyncTrainStateSaver:
    """Asynchronous :func:`save_train_state`: serialization overlaps
    training instead of stalling the step loop.

    ``save`` returns once orbax's AsyncCheckpointer has copied the
    state device-to-host (its documented contract — THIS is what makes
    continuing to train safe: the fused step's buffer donation deletes
    the old device arrays on the next call, so the copy must complete
    before the loop resumes, and it does, inside ``save``).  The disk
    write then proceeds on background threads.  A second ``save``
    before the first finishes blocks until it completes (one in-flight
    write per saver).  Call ``wait`` (or close the saver) before
    reading the checkpoint or exiting::

        saver = AsyncTrainStateSaver()
        for i, batch in enumerate(loader):
            loss = step(*batch)
            if i % 1000 == 0:
                saver.save(f"ckpt/step_{i}", step)
        saver.close()

    Restore with the synchronous :func:`restore_train_state`.
    """

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, path: str, step) -> None:
        import os

        import orbax.checkpoint as ocp

        self._ckptr.save(os.path.abspath(path),
                         args=ocp.args.StandardSave(step.state),
                         force=True)

    def wait(self) -> None:
        """Block until the in-flight write (if any) is durable."""
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def restore_train_state(path: str, step) -> None:
    """Restore a :func:`save_train_state` checkpoint into ``step.state``,
    preserving each array's CURRENT sharding (a ZeRO step restores its
    shards in place).  The step must be built with the same model/
    optimizer config the checkpoint was written from."""
    import orbax.checkpoint as ocp

    import os

    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=x.sharding)
        if isinstance(x, jax.Array) else x,
        step.state)
    ckptr = ocp.StandardCheckpointer()
    step.state = ckptr.restore(os.path.abspath(path), abstract)
