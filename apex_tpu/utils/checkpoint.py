"""Checkpoint save/load — the ``torch.save``/``torch.load`` role for the
three-part {model, optimizer, amp} checkpoint the reference documents
(README.md:59-99 there; amp state restore after ``amp.initialize`` with the
same opt_level for bitwise-accurate resume).

Device arrays are fetched to host numpy at save time (one sync, like
torch.save) and the container is pickled; loaders re-device through the
existing ``load_state_dict`` paths which call ``jnp.asarray``.

Resume exactness: scaler state, fp32 model weights (O2's fp32 state-dict
hook) and optimizer slots round-trip exactly; O2 *master* weights are
lazily re-derived from the fp16 model params after restore, so post-resume
trajectories can drift at fp16 rounding scale — same property as the
reference's documented O2 workflow.  For exact fp32-master checkpoints use
the legacy ``fp16_utils.FP16_Optimizer.state_dict``, which stores the
fp32 groups explicitly.
"""
from __future__ import annotations

import pickle

import jax
import numpy as np


def _to_host(tree):
    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(conv, tree)


def save_checkpoint(path: str, **components):
    """``save_checkpoint(path, model=model.state_dict(), optimizer=
    opt.state_dict(), amp=amp.state_dict(), epoch=...)`` — any picklable
    values; jax arrays anywhere in the trees are fetched to host first."""
    with open(path, "wb") as f:
        pickle.dump({k: _to_host(v) for k, v in components.items()}, f)


def load_checkpoint(path: str) -> dict:
    """Load a checkpoint written by :func:`save_checkpoint`.  Arrays come
    back as host numpy; feed the sub-dicts to the matching
    ``load_state_dict`` (model / optimizer / amp), which re-device them."""
    with open(path, "rb") as f:
        return pickle.load(f)
