from .checkpoint import (  # noqa: F401
    AsyncTrainStateSaver, load_checkpoint, restore_train_state,
    save_checkpoint, save_train_state)
