from .checkpoint import (  # noqa: F401
    AsyncTrainStateSaver, CheckpointCorruptError, load_checkpoint,
    restore_train_state, save_checkpoint, save_train_state)
from ..runtime.resilience import (  # noqa: F401 — resilience surface
    BadStepGuard, CheckpointManager, TrainingDivergedError)
