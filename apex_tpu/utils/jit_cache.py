"""Per-model compiled-run cache shared by the decode entry points
(models/gpt.py generate, models/seq2seq.py seq2seq_generate,
inference/speculative.py speculative_generate).

The invariants, in one place so the three callers cannot drift:

* the PARAMETER-OBJECT id tuple is part of the key — each compiled
  ``run`` closure zips ITS parameter list against the caller's values,
  so an entry is only valid while the model's parameter set is the one
  it closed over.  Applying/removing LoRA (or any Parameter swap) must
  MISS: a stale hit misaligns the zip and silently reads wrong weights.
* each entry pins the parameter objects it keyed on, so ids cannot be
  recycled into false hits while the entry lives.
* pop + reinsert on hit = LRU; the cache is capped so dead parameter
  sets (and their pinned XLA executables) cannot accumulate for the
  model's lifetime.
"""
from __future__ import annotations


def compiled_run_cache(model, attr, cfg, pinned_objs, build_fn, cap=16):
    """Return the compiled callable for ``cfg``, building it with
    ``build_fn()`` on a miss.

    ``attr``: name of the dict attribute holding the cache on ``model``;
    ``cfg``: hashable config EXCLUDING the parameter ids (appended
    here); ``pinned_objs``: the Parameter/Buffer objects the compiled
    closure zips against — their ids join the key and the entry holds
    the refs; ``cap``: max entries (oldest evicted first).
    """
    cache = getattr(model, attr, None)
    if cache is None:
        cache = {}
        setattr(model, attr, cache)
    key = (*cfg, tuple(id(o) for o in pinned_objs))
    entry = cache.pop(key, None)    # pop + reinsert = LRU refresh
    if entry is None:
        while len(cache) >= cap:
            cache.pop(next(iter(cache)))
        entry = (list(pinned_objs), build_fn())
    cache[key] = entry
    return entry[1]
