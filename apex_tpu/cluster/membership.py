"""Cluster membership: presence, heartbeats, and epoch-numbered views.

Grown out of ``parallel.distributed``'s presence registry (which
recorded "rank R checked in once" so a collective timeout could name
missing peers): here presence is CONTINUOUS — each member re-asserts
liveness by heartbeat, and the coordinator condenses the heartbeat
table into an **epoch-numbered membership view**: an immutable
``(epoch, members)`` snapshot that only ever advances.  Everything
downstream (the planner's fleet, the executor's dispatch tags, the
resharded restore) keys off the view's epoch, never off raw process
ids — that is the invariant the CLUSTER-ASSUME lint rule enforces.

Key layout (all under ``apex_tpu/cluster/``):

====================================  ==================================
key                                   value
====================================  ==================================
``members/<id>``                      the member's registration record
                                      (host spec; opaque to the
                                      protocol)
``hb/<id>``                           last heartbeat timestamp (clock
                                      units of the deployment's shared
                                      clock)
``epoch``                             the monotonic epoch counter —
                                      PERSISTED here so a restarted
                                      coordinator continues, never
                                      rewinds
``view/current``                      JSON of the live
                                      :class:`MembershipView`
``view/<epoch>``                      history: the view each epoch
                                      introduced
``ack/<epoch>/<id>``                  member ``<id>`` has adopted epoch
                                      ``<epoch>`` (the agreement half of
                                      detect→agree→replan→reshard)
====================================  ==================================

Chaos hooks ``host.loss`` and ``heartbeat.delay`` fire in
:meth:`Member.beat` — a ``"kill"`` is the simulated host death
(the in-process simulation converts it at the member boundary into
"this member's process is gone"), and a numeric ``heartbeat.delay``
result skews the written timestamp backwards, which under the
coordinator's ``miss_threshold`` must NOT cost the member its seat.
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Optional, Tuple

from ..runtime import chaos as _chaos
from .kvstore import KVStore

PREFIX = "apex_tpu/cluster/"


@dataclass(frozen=True)
class MembershipView:
    """One immutable epoch of cluster membership."""

    epoch: int
    members: Tuple[str, ...]

    def to_json(self) -> str:
        return json.dumps({"epoch": self.epoch,
                           "members": list(self.members)})

    @classmethod
    def from_json(cls, raw: str) -> "MembershipView":
        obj = json.loads(raw)
        return cls(epoch=int(obj["epoch"]),
                   members=tuple(obj["members"]))


def current_view(kv: KVStore) -> Optional[MembershipView]:
    raw = kv.get(f"{PREFIX}view/current")
    return MembershipView.from_json(raw) if raw else None


def current_epoch(kv: KVStore) -> int:
    """The persisted epoch counter (0 before any view is published)."""
    raw = kv.get(f"{PREFIX}epoch")
    return int(raw) if raw else 0


class Member:
    """One cluster member's presence agent.

    ``member_id`` is the stable identity ("host0", or a rank string);
    ``spec`` is an opaque registration record (e.g. the member's chip
    type and device count — the coordinator hands it to the planner as
    fleet metadata).  ``clock`` is injectable so tier-1 tests advance
    time deterministically; production uses ``time.monotonic`` against
    a per-deployment shared KV.
    """

    def __init__(self, kv: KVStore, member_id: str, *, spec: str = "",
                 clock=time.monotonic):
        self.kv = kv
        self.member_id = str(member_id)
        self.spec = spec
        self.clock = clock
        self.alive = False

    # -- lifecycle ---------------------------------------------------------
    def join(self):
        """Register + first heartbeat: after this the next coordinator
        scan includes the member in the view."""
        self.kv.set(f"{PREFIX}members/{self.member_id}", self.spec or "{}")
        self.alive = True
        self.beat()
        return self

    def leave(self):
        """Graceful departure: deregister so the next scan drops the
        member without waiting out ``miss_threshold``."""
        self.alive = False
        self.kv.delete(f"{PREFIX}members/{self.member_id}")
        self.kv.delete(f"{PREFIX}hb/{self.member_id}")

    # -- heartbeat ---------------------------------------------------------
    def beat(self):
        """Write one heartbeat.  Chaos: ``host.loss`` (``"kill"`` = this
        host dies — the heartbeat never lands and the member must drop
        from the next epoch once ``miss_threshold`` scans miss it);
        ``heartbeat.delay`` (a numeric result — a callable action's
        return, or the controller's ``delay_s`` — skews the timestamp
        backwards, simulating a paused-but-alive host)."""
        if not self.alive:
            raise RuntimeError(
                f"member {self.member_id!r} is not joined/alive")
        skew = 0.0
        if _chaos.active():
            _chaos.hook("host.loss", member=self.member_id)
            res = _chaos.hook("heartbeat.delay", member=self.member_id)
            if isinstance(res, (int, float)) and not isinstance(res, bool):
                skew = float(res)
        self.kv.set(f"{PREFIX}hb/{self.member_id}",
                    repr(self.clock() - skew))

    # -- agreement ---------------------------------------------------------
    def ack(self, view: MembershipView):
        """Adopt ``view``: the member-side half of agree-on-surviving-
        topology.  The coordinator (or the cluster runtime) waits for
        every surviving member's ack before declaring the epoch agreed
        and replanning onto it."""
        self.kv.set(f"{PREFIX}ack/{view.epoch}/{self.member_id}", "1")

    def latest_view(self) -> Optional[MembershipView]:
        return current_view(self.kv)
