"""The multi-host elastic cycle: detect → agree → replan → reshard.

``runtime.elastic`` recovers ONE process onto whatever device set came
back.  This module lifts that loop across processes: hosts assert
liveness through :mod:`~apex_tpu.cluster.membership`, a
:class:`~apex_tpu.cluster.coordinator.Coordinator` condenses heartbeats
into epoch-numbered views, and on a membership change the surviving
fleet acks the new view, re-plans for its (possibly heterogeneous)
device union, and streams the newest schema-3 checkpoint's shards into
the new layout — no host ever materializes full state.

Tier-1 runs the whole cycle in ONE process: :class:`ClusterTrainer`
simulates ``n_hosts`` member agents over a shared
:class:`~apex_tpu.cluster.kvstore.MemoryKV` and a
:class:`SimClock`, each owning a slice of the 8-virtual-CPU-device
mesh.  The chaos hooks (``host.loss``, ``coordinator.loss``,
``heartbeat.delay`` — see ``runtime/chaos.py``) drive failures
deterministically; ``bench.py --cluster`` additionally spawns REAL OS
processes heartbeating over a :class:`~apex_tpu.cluster.kvstore.FileKV`
(:func:`spawn_member_process`).

Process-boundary rule for :class:`~apex_tpu.runtime.chaos.ChaosKilled`:
the harness forbids catching a kill to continue the killed operation —
and the simulation honors that by converting the kill AT the process
boundary instead.  A member felled in :meth:`ClusterTrainer.tick` stays
dead (its agent never beats again); a felled coordinator is replaced by
a NEW ``Coordinator`` object over the same KV store, exactly what a
restarted coordinator process would construct.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Callable, Optional

from ..observe import registry as _obs
from ..observe import spans as _spans
from ..runtime import chaos as _chaos
from ..runtime import executor as _executor
from ..runtime.elastic import ElasticTrainer
from .coordinator import Coordinator
from .kvstore import KVStore, MemoryKV
from .membership import PREFIX, Member, MembershipView, current_view


class SimClock:
    """Deterministic time source shared by members and coordinator:
    call it for "now", :meth:`advance` to move time forward.  Tests
    drive heartbeat deadlines without ever sleeping."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def __call__(self) -> float:
        return self._t

    def advance(self, seconds: float) -> float:
        self._t += float(seconds)
        return self._t


class SimHost:
    """One simulated host: a membership agent plus the device slice it
    owns and the chip spec it registers (``chip`` name from
    ``parallel.auto.CHIPS``; ``scale`` < 1 declares a straggler)."""

    def __init__(self, member: Member, devices, *, chip: str = "cpu",
                 scale: float = 1.0):
        self.member = member
        self.devices = list(devices)
        self.chip = chip
        self.scale = float(scale)

    @property
    def member_id(self) -> str:
        return self.member.member_id

    @property
    def alive(self) -> bool:
        return self.member.alive


def _host_spec(chip: str, scale: float, n_devices: int) -> str:
    return json.dumps({"chip": chip, "scale": scale,
                       "n_devices": n_devices})


def beat_and_scan(kv: KVStore, clock: SimClock, members, coordinator,
                  make_coordinator, *, advance_s: float,
                  fallback_view: Optional[MembershipView] = None):
    """One membership cycle, shared by :class:`ClusterTrainer` and the
    serve fleet (:class:`apex_tpu.serve.elastic.ServeFleet`): advance
    the clock, every live member beats, the coordinator scans.
    ``ChaosKilled`` converts at the process boundary exactly as the
    module docstring demands — a felled member is marked dead and
    reported (its agent never beats again); a felled coordinator is
    replaced by ``make_coordinator()`` (what a restarted coordinator
    process would construct over the same store) and the previously
    published view stands until its first scan.  Returns
    ``(view, coordinator, felled_member_ids)``."""
    clock.advance(advance_s)
    felled = []
    for m in members:
        if not m.alive:
            continue
        try:
            m.beat()
        except _chaos.ChaosKilled:
            m.alive = False              # the host process is gone
            felled.append(m.member_id)
    try:
        view = coordinator.scan()
    except _chaos.ChaosKilled:
        coordinator = make_coordinator()
        view = current_view(kv) or fallback_view
    return view, coordinator, felled


def fleet_for_members(kv: KVStore, members) -> "object":
    """Build the planner :class:`~apex_tpu.parallel.auto.Fleet` from the
    REGISTERED specs of ``members`` (the kv registration records, not
    local host objects — the coordinator plans from what hosts declared
    at join time)."""
    from ..parallel.auto import CHIPS, Fleet
    specs = []
    for mid in members:
        raw = kv.get(f"{PREFIX}members/{mid}") or "{}"
        try:
            rec = json.loads(raw)
        except (TypeError, ValueError):
            rec = {}
        chip = CHIPS.get(rec.get("chip", "cpu"), CHIPS["cpu"])
        spec = chip.scaled(float(rec.get("scale", 1.0)))
        specs.extend([spec] * int(rec.get("n_devices", 1)))
    return Fleet(specs=tuple(specs))


class ClusterTrainer:
    """Multi-host elastic training, simulated in one process.

    The global device set splits into ``n_hosts`` contiguous slices;
    each slice belongs to one :class:`SimHost` whose agent heartbeats
    through the shared ``kv``.  :meth:`join` publishes epoch 1;
    :meth:`tick` runs one heartbeat+scan cycle (where chaos fells hosts
    or the coordinator); :meth:`recover` runs the agree→replan→reshard
    half onto the surviving fleet.  ``host_scales`` declares per-host
    speed factors (straggler stand-ins) that flow into the planner's
    heterogeneous fleet; remaining keyword arguments go to the inner
    :class:`~apex_tpu.runtime.elastic.ElasticTrainer`.
    """

    def __init__(self, manager, model, optimizer, loss_fn: Callable, *,
                 example_batch, n_hosts: int = 2, devices=None,
                 kv: Optional[KVStore] = None,
                 clock: Optional[SimClock] = None,
                 deadline_s: float = 0.25, miss_threshold: int = 2,
                 chip: str = "cpu", host_scales=None,
                 plan_options: Optional[dict] = None,
                 plan_filter: Optional[Callable] = None, **step_kwargs):
        from ..parallel.auto import _resolve_devices
        devs = _resolve_devices(devices)
        if n_hosts < 1 or n_hosts > len(devs):
            raise ValueError(f"n_hosts={n_hosts} with {len(devs)} devices")
        if len(devs) % n_hosts:
            raise ValueError(f"{len(devs)} devices do not split evenly "
                             f"across {n_hosts} hosts")
        scales = list(host_scales or [])
        if scales and len(scales) != n_hosts:
            raise ValueError(f"host_scales needs {n_hosts} entries, "
                             f"got {len(scales)}")
        self.kv = kv if kv is not None else MemoryKV()
        self.clock = clock if clock is not None else SimClock()
        self.deadline_s = float(deadline_s)
        self.miss_threshold = int(miss_threshold)
        per = len(devs) // n_hosts
        self.hosts = []
        for i in range(n_hosts):
            scale = float(scales[i]) if scales else 1.0
            member = Member(
                self.kv, f"host{i}", clock=self.clock,
                spec=_host_spec(chip, scale, per))
            self.hosts.append(SimHost(member, devs[i * per:(i + 1) * per],
                                      chip=chip, scale=scale))
        self.coordinator = Coordinator(
            self.kv, deadline_s=self.deadline_s,
            miss_threshold=self.miss_threshold, clock=self.clock)
        self.trainer = ElasticTrainer(
            manager, model, optimizer, loss_fn,
            example_batch=example_batch, plan_options=plan_options,
            plan_filter=plan_filter, **step_kwargs)
        self.view: Optional[MembershipView] = None
        self.telemetry: dict = {}

    # -- membership --------------------------------------------------------
    def join(self) -> MembershipView:
        """All hosts register + first-beat; the coordinator publishes
        epoch 1 and every member acks it."""
        for h in self.hosts:
            h.member.join()
        view = self.coordinator.scan()
        for h in self.hosts:
            if h.alive:
                h.member.ack(view)
        self.view = view
        return view

    def _make_coordinator(self) -> Coordinator:
        return Coordinator(
            self.kv, deadline_s=self.deadline_s,
            miss_threshold=self.miss_threshold, clock=self.clock)

    def tick(self, advance_s: Optional[float] = None) -> MembershipView:
        """One cluster cycle: advance the clock, every live host beats,
        the coordinator scans.  Chaos kills convert at the process
        boundary (module docstring): a felled host stays dead, a felled
        coordinator is rebuilt over the same store and scans next tick
        (its successor inherits the persisted epoch, not the miss
        counters)."""
        if advance_s is None:
            advance_s = self.deadline_s / 2
        view, self.coordinator, _felled = beat_and_scan(
            self.kv, self.clock, [h.member for h in self.hosts],
            self.coordinator, self._make_coordinator,
            advance_s=advance_s, fallback_view=self.view)
        return view

    def membership_changed(self) -> bool:
        """True when the published view is newer than the one training
        last agreed to."""
        view = current_view(self.kv)
        return view is not None and (
            self.view is None or view.epoch != self.view.epoch)

    # -- recovery ----------------------------------------------------------
    def surviving_devices(self, view: MembershipView) -> list:
        return [d for h in self.hosts if h.member_id in view.members
                for d in h.devices]

    def recover(self) -> int:
        """The agree→replan→reshard half of the cycle: every surviving
        member acks the current view; once the coordinator sees full
        agreement, the inner elastic trainer re-plans for the survivors'
        device union (a heterogeneous fleet when host scales differ) and
        streams the newest valid checkpoint into the new layout.
        Returns the step training continues from."""
        t0 = time.perf_counter()
        view = current_view(self.kv)
        if view is None:
            view = self.coordinator.scan()
        for h in self.hosts:
            if h.alive and h.member_id in view.members:
                h.member.ack(view)
        if not self.coordinator.acked(view):
            missing = [m for m in view.members
                       if not any(h.member_id == m and h.alive
                                  for h in self.hosts)]
            raise RuntimeError(
                f"cluster epoch {view.epoch} not agreed: members "
                f"{missing} never acked (still listed but not alive?)")
        detect_ms = (time.perf_counter() - t0) * 1e3
        devs = self.surviving_devices(view)
        if not devs:
            raise RuntimeError(
                f"cluster epoch {view.epoch}: no surviving devices")
        self.trainer.plan_options["fleet"] = fleet_for_members(
            self.kv, view.members)
        with _spans.span("cluster.recover", epoch=view.epoch,
                         members=len(view.members)):
            start = self.trainer.restore(devices=devs)
        _executor.set_cluster_epoch(view.epoch)
        self.view = view
        restore_stats = dict(
            getattr(self.trainer.manager, "last_restore_stats", {}) or {})
        self.telemetry = {
            "epoch": view.epoch,
            "members": list(view.members),
            "n_devices": len(devs),
            "detect_ms": round(detect_ms, 3),
            "replan_ms": self.trainer.telemetry.get("replan_ms"),
            "reshard_ms": self.trainer.telemetry.get("reshard_ms"),
            "resume_step": self.trainer.resume_step,
            "restore_mode": restore_stats.get("mode"),
            "restore_peak_host_bytes":
                restore_stats.get("peak_host_bytes"),
        }
        _obs.event("cluster.restore", **self.telemetry)
        return start

    # -- training ----------------------------------------------------------
    def save(self, step_no: int, **extra) -> str:
        return self.trainer.save(step_no, **extra)

    def __call__(self, *batch):
        return self.trainer(*batch)

    @property
    def plan(self):
        return self.trainer.plan


def spawn_member_process(kv_dir: str, member_id: str, *,
                         interval_s: float = 0.05, beats: int = 100,
                         spec: str = "") -> subprocess.Popen:
    """Spawn a REAL OS process that joins membership over a
    :class:`~apex_tpu.cluster.kvstore.FileKV` at ``kv_dir`` and
    heartbeats ``beats`` times at ``interval_s`` — the genuinely
    multi-process half of ``bench.py --cluster`` (a coordinator in the
    parent detects these children exactly as it detects simulated
    members).  The child exits cleanly after its beats run out, which a
    coordinator observes as host loss."""
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})\n"
        "from apex_tpu.cluster.kvstore import FileKV\n"
        "from apex_tpu.cluster.membership import Member\n"
        f"m = Member(FileKV({kv_dir!r}), {member_id!r}, spec={spec!r})\n"
        "m.join()\n"
        f"for _ in range({int(beats)}):\n"
        f"    time.sleep({float(interval_s)!r})\n"
        "    m.beat()\n")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
