"""The cluster coordinator: heartbeat-driven failure detection and
epoch publication.

One coordinator scans the heartbeat table at a fixed cadence and
publishes a new :class:`~apex_tpu.cluster.membership.MembershipView`
whenever the live set changes.  Two properties carry the protocol:

* **Consecutive-miss detection.**  A member is declared dead only after
  ``miss_threshold`` CONSECUTIVE scans find its heartbeat stale (older
  than ``deadline_s``).  A single delayed heartbeat — GC pause, slow
  NFS, the ``heartbeat.delay`` chaos action — resets to zero the moment
  a fresh beat lands, so transient skew never costs a member its seat
  (the false-positive guard tier-1 pins).
* **Epochs survive the coordinator.**  The epoch counter lives in the
  KV store, not in the coordinator object; a replacement coordinator
  built over the same store (the ``coordinator.loss`` recovery path)
  continues from the persisted value — epochs are monotonic across
  coordinator deaths, so "which epoch is newer" is always decidable.

The coordinator is deliberately soft-state otherwise: miss counters
rebuild from scratch after a coordinator loss (costing at worst
``miss_threshold`` extra scans of detection latency, never a wrong
answer).
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from ..observe import registry as _obs
from ..runtime import chaos as _chaos
from .kvstore import KVStore
from .membership import PREFIX, MembershipView, current_epoch, current_view


class Coordinator:
    """Failure detector + epoch publisher over a :class:`KVStore`.

    ``deadline_s`` is how stale a heartbeat may be before a scan counts
    a miss (typically 2× the members' beat interval); ``miss_threshold``
    is how many consecutive missing scans fell a member.  ``clock`` must
    be the same clock the members stamp heartbeats with (injectable for
    deterministic tests)."""

    def __init__(self, kv: KVStore, *, deadline_s: float = 1.0,
                 miss_threshold: int = 2, clock=time.monotonic):
        if miss_threshold < 1:
            raise ValueError(
                f"miss_threshold must be >= 1, got {miss_threshold}")
        self.kv = kv
        self.deadline_s = float(deadline_s)
        self.miss_threshold = int(miss_threshold)
        self.clock = clock
        #: consecutive stale-heartbeat scans per member (soft state)
        self.misses: Dict[str, int] = {}

    # -- detection ---------------------------------------------------------
    def registered(self) -> list:
        n = len(f"{PREFIX}members/")
        return sorted(k[n:] for k in self.kv.scan(f"{PREFIX}members/"))

    def scan(self) -> MembershipView:
        """One failure-detection pass: read every registered member's
        heartbeat, update consecutive-miss counters, and publish a new
        epoch iff the live set changed.  Returns the current (possibly
        fresh) view.  Chaos hook ``coordinator.loss`` fires first —
        ``"kill"`` is the coordinator dying mid-duty; the successor is a
        new :class:`Coordinator` over the same store."""
        if _chaos.active():
            _chaos.hook("coordinator.loss")
        now = self.clock()
        view = current_view(self.kv)
        alive = []
        for member in self.registered():
            raw = self.kv.get(f"{PREFIX}hb/{member}")
            fresh = raw is not None and \
                (now - float(raw)) <= self.deadline_s
            if fresh:
                self.misses[member] = 0
            elif member not in self.misses and view is not None \
                    and member not in view.members:
                # a successor coordinator starts with empty counters; a
                # registered-but-stale member the published view already
                # DROPPED stays presumed dead (only a fresh beat
                # readmits it) — otherwise every coordinator restart
                # would resurrect dead members for one bogus epoch
                self.misses[member] = self.miss_threshold
            else:
                self.misses[member] = self.misses.get(member, 0) + 1
            if self.misses[member] < self.miss_threshold:
                alive.append(member)
        if view is not None and tuple(alive) == view.members:
            return view
        return self._publish(alive, prev=view)

    def _publish(self, alive: list, prev: Optional[MembershipView]
                 ) -> MembershipView:
        epoch = current_epoch(self.kv) + 1
        view = MembershipView(epoch=epoch, members=tuple(alive))
        # counter first, view second: a coordinator killed between the
        # two burns an epoch number, which is harmless — monotonicity is
        # the invariant, density is not
        self.kv.set(f"{PREFIX}epoch", str(epoch))
        self.kv.set(f"{PREFIX}view/{epoch}", view.to_json())
        self.kv.set(f"{PREFIX}view/current", view.to_json())
        _obs.event("cluster.epoch", epoch=epoch, members=list(alive),
                   lost=sorted(set(prev.members) - set(alive))
                   if prev else [],
                   joined=sorted(set(alive) -
                                 set(prev.members if prev else ())))
        return view

    # -- agreement ---------------------------------------------------------
    def acked(self, view: MembershipView) -> bool:
        """True when every member of ``view`` has adopted it."""
        n = len(f"{PREFIX}ack/{view.epoch}/")
        got = {k[n:] for k in self.kv.scan(f"{PREFIX}ack/{view.epoch}/")}
        return set(view.members) <= got
