"""apex_tpu.cluster — multi-host elastic runtime (docs/cluster.md).

The detect→agree→replan→reshard cycle across processes: KV-backed
membership with heartbeats and epoch-numbered views
(:mod:`~apex_tpu.cluster.membership`,
:mod:`~apex_tpu.cluster.coordinator`), pluggable coordination substrates
(:mod:`~apex_tpu.cluster.kvstore` — in-memory for tier-1 simulation,
file-backed for real multi-process runs, the ``jax.distributed``
coordinator service for pods), and the :class:`ClusterTrainer` that
composes them with ``runtime.elastic`` and the planner's heterogeneous
fleets.  This package (plus ``parallel.distributed``) is the ONE
sanctioned home for process-topology assumptions — the CLUSTER-ASSUME
lint rule holds everything else to that.
"""
from .kvstore import (  # noqa: F401
    FileKV, JaxCoordinatorKV, KVStore, MemoryKV, default_kv)
from .membership import (  # noqa: F401
    PREFIX, Member, MembershipView, current_epoch, current_view)
from .coordinator import Coordinator  # noqa: F401
from .runtime import (  # noqa: F401
    ClusterTrainer, SimClock, SimHost, beat_and_scan, fleet_for_members,
    spawn_member_process)

__all__ = [
    "PREFIX", "KVStore", "MemoryKV", "FileKV", "JaxCoordinatorKV",
    "default_kv",
    "Member", "MembershipView", "current_epoch", "current_view",
    "Coordinator", "ClusterTrainer", "SimClock", "SimHost",
    "beat_and_scan", "fleet_for_members", "spawn_member_process",
]
