"""Coordinator KV stores — the substrate the cluster membership
protocol runs on.

Every piece of cluster state (member registrations, heartbeat
timestamps, the epoch counter, membership views, acks) is a string
value under a string key in ONE logical store, so the same protocol
code runs against three backends:

* :class:`MemoryKV` — in-process dict; the deterministic tier-1 test
  substrate (multi-member simulation with a fake clock).
* :class:`FileKV` — a directory of one-file-per-key entries with
  atomic writes; crosses REAL process boundaries with no server, which
  is how ``bench.py --cluster`` runs heartbeat members as separate OS
  processes and how a shared filesystem can stand in for a coordinator.
* :class:`JaxCoordinatorKV` — the ``jax.distributed`` coordinator
  service's key-value client, for actual multi-host pods
  (``parallel.distributed``'s presence registry goes through this).

Keys are flat strings with ``/`` separators by convention
(``apex_tpu/cluster/<namespace>/...``); ``scan(prefix)`` is the only
query primitive the protocol needs.
"""
from __future__ import annotations

import os
import threading
import urllib.parse
from typing import Dict, Optional


class KVStore:
    """Protocol: the four operations the membership layer uses."""

    def get(self, key: str) -> Optional[str]:
        raise NotImplementedError

    def set(self, key: str, value: str) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def scan(self, prefix: str) -> Dict[str, str]:
        """Every ``key: value`` whose key starts with ``prefix``."""
        raise NotImplementedError


class MemoryKV(KVStore):
    """Dict-backed store for in-process multi-member simulation."""

    def __init__(self):
        self._data: Dict[str, str] = {}
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            return self._data.get(key)

    def set(self, key, value):
        with self._lock:
            self._data[key] = str(value)

    def delete(self, key):
        with self._lock:
            self._data.pop(key, None)

    def scan(self, prefix):
        with self._lock:
            return {k: v for k, v in self._data.items()
                    if k.startswith(prefix)}


class FileKV(KVStore):
    """One-file-per-key store under a directory — crosses process
    boundaries through the filesystem.

    Writes are atomic (tmp + rename, the same durability idiom as the
    checkpoint writer) so a reader never sees a torn value; keys are
    percent-encoded into filenames, so any string key works."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory,
                            urllib.parse.quote(key, safe=""))

    def get(self, key):
        try:
            with open(self._path(key), "r") as f:
                return f.read()
        except (FileNotFoundError, OSError):
            return None

    def set(self, key, value):
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(str(value))
        os.replace(tmp, path)

    def delete(self, key):
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def scan(self, prefix):
        out = {}
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for name in names:
            if ".tmp." in name:
                continue
            key = urllib.parse.unquote(name)
            if key.startswith(prefix):
                v = self.get(key)
                if v is not None:
                    out[key] = v
        return out


class JaxCoordinatorKV(KVStore):
    """The ``jax.distributed`` coordinator's KV service, adapted to the
    protocol.  Only constructible after ``init_distributed`` has run
    (:func:`client` returns None otherwise); the coordinator service has
    no native scan, so :meth:`scan` walks an index key the setters
    maintain — adequate for the small, slow-changing key sets the
    membership protocol keeps."""

    _INDEX = "apex_tpu/cluster/__index__"

    def __init__(self, client=None):
        if client is None:
            client = self.client()
        if client is None:
            raise RuntimeError(
                "no jax.distributed coordinator client — call "
                "apex_tpu.parallel.init_distributed() first, or use "
                "FileKV/MemoryKV")
        self._client = client

    @staticmethod
    def client():
        """The live coordinator client, or None (single process)."""
        try:
            from jax._src import distributed as _jd
            return _jd.global_state.client
        except Exception:
            return None

    def _index(self):
        try:
            raw = self._client.key_value_try_get(self._INDEX)
        except Exception:
            return []
        return [k for k in (raw or "").split("\n") if k]

    def get(self, key):
        try:
            return self._client.key_value_try_get(key)
        except Exception:
            return None

    def set(self, key, value):
        self._client.key_value_set(key, str(value))
        idx = self._index()
        if key not in idx:
            self._client.key_value_set(self._INDEX,
                                       "\n".join(idx + [key]))

    def delete(self, key):
        # the coordinator service has no delete; tombstone instead
        try:
            self._client.key_value_set(key, "")
        except Exception:
            pass

    def scan(self, prefix):
        out = {}
        for key in self._index():
            if key.startswith(prefix):
                v = self.get(key)
                if v:
                    out[key] = v
        return out


def default_kv() -> KVStore:
    """Resolve the ambient coordination store, strongest first: the
    live ``jax.distributed`` coordinator service when one is
    initialized; else the :class:`FileKV` directory the multiproc
    launcher exported (``APEX_TPU_CLUSTER_KV``); else a fresh private
    :class:`MemoryKV` (single-process)."""
    client = JaxCoordinatorKV.client()
    if client is not None:
        return JaxCoordinatorKV(client)
    directory = os.environ.get("APEX_TPU_CLUSTER_KV")
    if directory:
        return FileKV(directory)
    return MemoryKV()
