"""EncdecMultiheadAttn (reference:
apex/contrib/multihead_attn/encdec_multihead_attn.py): encoder-decoder
attention with separate q and interleaved-kv projections.  Same impl
selection as SelfMultiheadAttn; returns (outputs, None)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...nn.modules import Module, _next_key
from ...nn.parameter import Parameter
from .attn_funcs import encdec_attn_func
from .self_multihead_attn import _AttnModule, _xavier_uniform


class EncdecMultiheadAttn(_AttnModule):
    def __init__(self, embed_dim, num_heads, dropout=0.0, bias=False,
                 include_norm_add=False, impl="fast",
                 tensor_parallel_axis=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        # Megatron head sharding over this mesh axis (same design as
        # SelfMultiheadAttn: full replicated weights, head-block slices
        # at trace time, f/g operators at the region edges)
        self.tensor_parallel_axis = tensor_parallel_axis
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim, \
            "embed_dim must be divisible by num_heads"
        assert not bias, \
            "ERROR! encdec multihead attention does not support biases!"
        self.bias = False
        self.include_norm_add = include_norm_add
        if impl not in ("fast", "default"):
            raise AssertionError(f"Unsupported impl: {impl} !")
        self.impl = impl
        self.scaling = self.head_dim ** -0.5

        self.in_proj_weight_q = Parameter(
            _xavier_uniform(_next_key(), (embed_dim, embed_dim)))
        self.in_proj_weight_kv = Parameter(
            _xavier_uniform(_next_key(), (2 * embed_dim, embed_dim)))
        self.out_proj_weight = Parameter(
            _xavier_uniform(_next_key(), (embed_dim, embed_dim)))
        if include_norm_add:
            self.lyr_nrm_gamma_weights = Parameter(
                jnp.ones((embed_dim,), jnp.float32))
            self.lyr_nrm_beta_weights = Parameter(
                jnp.zeros((embed_dim,), jnp.float32))

    def tp_sharded_params(self):
        """Block-sparse-gradient parameters under tensor parallelism
        (see SelfMultiheadAttn.tp_sharded_params): q/kv projections shard
        rows per head, the output projection shards columns."""
        return [self.in_proj_weight_q, self.in_proj_weight_kv,
                self.out_proj_weight]

    def forward(self, ctx, query, key, value=None, key_padding_mask=None,
                need_weights=False, attn_mask=None, is_training=None):
        if key_padding_mask is not None:
            assert attn_mask is None, \
                "ERROR attn_mask and key_padding_mask should not be both " \
                "defined!"
            mask, use_time_mask = key_padding_mask, False
        elif attn_mask is not None:
            mask, use_time_mask = attn_mask, True
        else:
            mask, use_time_mask = None, False

        if is_training is None:
            is_training = ctx.training and self.training
        drop_key = ctx.next_key() if (is_training and self.dropout > 0.0) \
            else None

        x = query
        if self.include_norm_add:
            from ...normalization import fused_layer_norm_affine
            x = fused_layer_norm_affine(
                x, ctx.value(self.lyr_nrm_gamma_weights),
                ctx.value(self.lyr_nrm_beta_weights),
                (self.embed_dim,), 1e-5)

        outputs = encdec_attn_func(
            use_time_mask, is_training, self.num_heads, self.scaling, x,
            key, ctx.value(self.in_proj_weight_q),
            ctx.value(self.in_proj_weight_kv),
            ctx.value(self.out_proj_weight), mask, self.dropout,
            key=drop_key, use_flash=(self.impl == "fast"),
            tensor_parallel_axis=self.tensor_parallel_axis)

        if self.include_norm_add:
            if is_training and self.dropout > 0.0:
                outputs = F.dropout(outputs, self.dropout, training=True,
                                    key=ctx.next_key())
            outputs = outputs + query
        return outputs, None
