"""SelfMultiheadAttn (reference:
apex/contrib/multihead_attn/self_multihead_attn.py:19-123).

API parity: same constructor args and (T, B, E) input layout; ``impl='fast'``
routes through the Pallas flash kernel (the ``fast_self_attn_func`` CUDA
extension analogue), ``impl='default'`` through the jnp batched-GEMM path;
``include_norm_add`` fuses a pre-LayerNorm and residual dropout-add
(fast_self_attn_norm_add_func analogue, built on FusedLayerNorm).
Returns ``(outputs, None)`` like the reference (:123).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...nn.modules import Module, _next_key
from ...nn.parameter import Parameter
from .attn_funcs import self_attn_func


def _xavier_uniform(key, shape):
    fan_out, fan_in = shape[0], shape[1]
    bound = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


class _AttnModule(Module):
    """Attention modules take keyword args (masks, flags) the tape's
    positional replay can't carry, and return a (outputs, None) tuple —
    their ``__call__`` therefore runs forward eagerly.  Differentiable use
    goes through ``forward(ctx, ...)`` from a parent module or the fused
    train step, which is also how the reference integrates them."""

    def __call__(self, *args, **kwargs):
        from ...nn.modules import Ctx, _next_key
        key = _next_key() if (self.training and self.dropout > 0.0) else None
        ctx = Ctx(env={}, stats_out=None, training=self.training, key=key)
        return self.forward(ctx, *args, **kwargs)


class SelfMultiheadAttn(_AttnModule):
    def __init__(self, embed_dim, num_heads, dropout=0.0, bias=False,
                 include_norm_add=False, impl="fast", causal=False,
                 seq_parallel_axis=None, seq_parallel_impl="ring",
                 tensor_parallel_axis=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        # causal=True applies the triangle in-kernel (decoder models) —
        # no O(S^2) mask operand; beyond the reference's surface
        self.causal = causal
        # sequence parallelism: when set, forward must run inside
        # shard_map with the time dim sharded on this mesh axis; attention
        # rides the ring (or Ulysses all-to-all) across devices
        self.seq_parallel_axis = seq_parallel_axis
        self.seq_parallel_impl = seq_parallel_impl
        # tensor parallelism: Megatron head sharding over this mesh axis;
        # parameters stay FULL (replicated) and each device slices its
        # head block at trace time (attn_funcs.self_attn_func)
        self.tensor_parallel_axis = tensor_parallel_axis
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim, \
            "embed_dim must be divisible by num_heads"
        self.bias = bias
        self.include_norm_add = include_norm_add
        if impl not in ("fast", "default"):
            raise AssertionError(f"Unsupported impl: {impl} !")
        self.impl = impl
        self.scaling = self.head_dim ** -0.5

        self.in_proj_weight = Parameter(
            _xavier_uniform(_next_key(), (3 * embed_dim, embed_dim)))
        self.out_proj_weight = Parameter(
            _xavier_uniform(_next_key(), (embed_dim, embed_dim)))
        if bias:
            assert impl != "fast", \
                "ERROR! The Fast implementation does not support biases!"
            self.in_proj_bias = Parameter(jnp.zeros((3 * embed_dim,),
                                                    jnp.float32))
            self.out_proj_bias = Parameter(jnp.zeros((embed_dim,),
                                                     jnp.float32))
        else:
            self.register_parameter("in_proj_bias", None)
            self.register_parameter("out_proj_bias", None)
        if include_norm_add:
            # both impls share the affine-LN parameter pair here (the
            # reference keeps a separate nn.LayerNorm for 'default'; one
            # parameterization keeps checkpoints interchangeable)
            self.lyr_nrm_gamma_weights = Parameter(
                jnp.ones((embed_dim,), jnp.float32))
            self.lyr_nrm_beta_weights = Parameter(
                jnp.zeros((embed_dim,), jnp.float32))

    def tp_sharded_params(self):
        """This module's parameters whose per-device gradients are
        block-sparse under tensor parallelism (the contract
        make_train_step(tp_axis=...) assembles by psum): the head-sharded
        QKV projection (rows) and the output projection (columns).  The
        model-family blocks extend this with their sharded MLP entries —
        keeping the attention subset HERE means a future layout change
        cannot desynchronize the GPT and BERT families."""
        ps = [self.in_proj_weight, self.out_proj_weight]
        if self.in_proj_bias is not None:
            ps.append(self.in_proj_bias)
        return ps

    def forward(self, ctx, query, key=None, value=None,
                key_padding_mask=None, need_weights=False, attn_mask=None,
                is_training=None):
        if key_padding_mask is not None:
            assert attn_mask is None, \
                "ERROR attn_mask and key_padding_mask should not be both " \
                "defined!"
            mask, use_time_mask = key_padding_mask, False
        elif attn_mask is not None:
            mask, use_time_mask = attn_mask, True
        else:
            mask, use_time_mask = None, False

        if is_training is None:
            is_training = ctx.training and self.training
        drop_key = ctx.next_key() if (is_training and self.dropout > 0.0) \
            else None
        # ring-SP dropout needs the PRE-FOLD (axis-replicated) key so the
        # global hash mask agrees on every sequence shard; same counter
        # as drop_key, so it equals the unsharded run's drop_key exactly
        sp_shared_key = None
        if (drop_key is not None and self.seq_parallel_axis is not None
                and ctx.shared_key is not None):
            sp_shared_key = jax.random.fold_in(ctx.shared_key,
                                               ctx._key_idx)

        x = query
        if self.include_norm_add:
            from ...normalization import fused_layer_norm_affine
            x = fused_layer_norm_affine(
                x, ctx.value(self.lyr_nrm_gamma_weights),
                ctx.value(self.lyr_nrm_beta_weights),
                (self.embed_dim,), 1e-5)

        outputs = self_attn_func(
            use_time_mask, is_training, self.num_heads, self.scaling, x,
            ctx.value(self.in_proj_weight), ctx.value(self.out_proj_weight),
            ctx.value(self.in_proj_bias) if self.bias else None,
            ctx.value(self.out_proj_bias) if self.bias else None,
            mask, self.dropout, key=drop_key,
            use_flash=(self.impl == "fast"), causal=self.causal,
            seq_parallel_axis=self.seq_parallel_axis,
            seq_parallel_impl=self.seq_parallel_impl,
            tensor_parallel_axis=self.tensor_parallel_axis,
            sp_shared_key=sp_shared_key)

        if self.include_norm_add:
            if is_training and self.dropout > 0.0:
                outputs = F.dropout(outputs, self.dropout, training=True,
                                    key=ctx.next_key())
            outputs = outputs + query
        return outputs, None
