"""apex.contrib.multihead_attn equivalent (reference
apex/contrib/multihead_attn/__init__.py)."""
from .attn_funcs import (  # noqa: F401
    encdec_attn_func,
    flash_attention,
    self_attn_func,
)
from .encdec_multihead_attn import EncdecMultiheadAttn  # noqa: F401
from .self_multihead_attn import SelfMultiheadAttn  # noqa: F401
