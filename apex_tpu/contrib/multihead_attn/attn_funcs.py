"""Attention functionals for contrib.multihead_attn.

``self_attn_func``/``encdec_attn_func`` mirror the reference's pure-torch
paths (apex/contrib/multihead_attn/self_multihead_attn_func.py:4-118,
encdec_multihead_attn_func.py) in jnp: fused QKV projection with the
reference's PER-HEAD INTERLEAVED weight layout (in_proj output reshaped to
(T, B·H, 3, D) — self_multihead_attn_func.py:35-38, i.e. weight rows grouped
[q_h, k_h, v_h] per head, NOT the torch [Q;K;V] block layout), batched
attention GEMMs, mask fill, softmax, dropout, output projection.

``flash_attention`` is the fast path (replacing the ``fast_*_multihead_attn``
CUDA extensions): a Pallas flash kernel on TPU
(apex_tpu/ops/pallas/attention.py), an equivalent jnp computation elsewhere.
Attention dropout rides IN-KERNEL on this path — a counter-based hash mask
regenerated in the backward (the analogue of the reference's fused Philox
dropout, csrc/multihead_attn/dropout.cuh) — so the flash path stays O(S)
memory with dropout active.  It composes with every mesh: under TP each
head-shard folds its axis index into the seed (per-rank streams); under
ring-SP the mask hashes GLOBAL coordinates from the replicated pre-shard
key, making the dropped positions bit-identical to the single-device
run; ulysses decorrelates per head-shard.  Only the materializing
'default' impl refuses dropout under TP (one shared key).  The
``_attn_with_dropout`` materializing path remains for the 'default'
impl (reference softmax.h parity).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from ...nn.functional import dropout_mask
from ...kernels.dispatch import pallas_mode
from ...kernels import attention as _k

_f32 = jnp.float32
_NEG = -1e30


def _flash_min_sk():
    """Key-length threshold below which compiled dispatch prefers XLA's
    own attention over the Pallas flash kernel — the kernel module owns
    the measured boundary (env override > ledger-measured win > the 512
    round-4 prior; see :func:`apex_tpu.kernels.attention.flash_min_sk`
    for the v5e receipts)."""
    return _k.flash_min_sk()


_XLA_SCORES_BYTE_CAP = _k.XLA_SCORES_BYTE_CAP


def _use_xla_attention(b, h, sq, sk):
    """Compiled-mode dispatch: take the materializing XLA path only when
    it is both faster (short keys) and memory-harmless (small total
    score tensor).  Kept as the shape-level oracle; ``flash_attention``
    itself decides through ``kernels.dispatch`` so ledger entries can
    override per shape."""
    return sk < _flash_min_sk() and \
        b * h * sq * sk * 4 <= _XLA_SCORES_BYTE_CAP


def attention_reference(q4, k4, v4, bias, causal, scale, window=None,
                        dropout_p=0.0, dropout_seed=None):
    """Plain-XLA attention, (B, H, S, D) layout; the fallback/oracle
    path.  ``window`` adds the Mistral band on top of ``causal``
    (position t sees keys in (t - window, t]).  ``dropout_p`` applies
    the SAME counter-based hash mask the Pallas kernels generate
    (ops/pallas/attention.dropout_keep_reference), so the two paths
    agree bit-for-bit on which probs drop for a given seed."""
    b, h, sq, d = q4.shape
    sk = k4.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q4.astype(_f32),
                   k4.astype(_f32)) * scale
    if bias is not None:
        s = s + bias[:, None].astype(_f32)
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        keep = rows >= cols
        if window is not None:
            keep = jnp.logical_and(keep, cols > rows - window)
        s = jnp.where(keep, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0:
        mult = _k.dropout_keep_reference(b * h, sq, sk, dropout_seed,
                                         dropout_p)
        p = p * jax.lax.stop_gradient(mult).reshape(b, h, sq, sk)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v4.astype(_f32)).astype(q4.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q4, k4, v4, bias, seed, causal, scale, interpret, window,
           dropout_p):
    out, _ = _flash_fwd_math(q4, k4, v4, bias, seed, causal, scale,
                             interpret, window, dropout_p)
    return out


def _flash_fwd_math(q4, k4, v4, bias, seed, causal, scale, interpret,
                    window, dropout_p):
    b, h, sq, d = q4.shape
    sk = k4.shape[2]
    q3 = q4.reshape(b * h, sq, d)
    k3 = k4.reshape(b * h, sk, d)
    v3 = v4.reshape(b * h, sk, d)
    bias3 = None
    if bias is not None:
        # kernel bias layout (B|1, Sq|1, Sk) broadcasts over heads by
        # repeating per head in the leading dim when per-batch
        bias3 = bias if bias.shape[0] == 1 else jnp.repeat(bias, h, axis=0)
    out3, lse = _k.flash_attention_fwd(q3, k3, v3, bias3, scale, causal,
                                       interpret=interpret, window=window,
                                       dropout_p=dropout_p,
                                       dropout_seed=seed)
    return out3.reshape(b, h, sq, d), (q3, k3, v3, bias3, out3, lse)


def _flash_vjp_fwd(q4, k4, v4, bias, seed, causal, scale, interpret, window,
                   dropout_p):
    out, res = _flash_fwd_math(q4, k4, v4, bias, seed, causal, scale,
                               interpret, window, dropout_p)
    return out, (res, q4.shape, k4.shape, bias, seed)


def _flash_vjp_bwd(causal, scale, interpret, window, dropout_p, saved, g):
    (q3, k3, v3, bias3, out3, lse), qshape, kshape, bias, seed = saved
    b, h, sq, d = qshape
    dq, dk, dv = _k.flash_attention_bwd(
        q3, k3, v3, bias3, out3, lse, g.reshape(b * h, sq, d), scale, causal,
        interpret=interpret, window=window, dropout_p=dropout_p,
        dropout_seed=seed)
    dbias = None if bias is None else jnp.zeros_like(bias)
    # int32 seed cotangent is float0 by JAX convention
    import numpy as _np

    dseed = None if seed is None else _np.zeros(_np.shape(seed),
                                                jax.dtypes.float0)
    return (dq.reshape(qshape), dk.reshape(kshape), dv.reshape(kshape),
            dbias, dseed)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q4, k4, v4, bias=None, causal=False, scale=None,
                    sliding_window=None, dropout_p=0.0, dropout_seed=None):
    """Fused scaled-dot-product attention, (B, H, S, D) layout.

    ``bias`` is an additive mask, broadcastable (B|1, Sq|1, Sk) — carries
    key-padding and attention masks; ``causal`` masks future timesteps
    in-kernel.  ``sliding_window`` (requires ``causal``) applies the
    Mistral band — position t sees keys in (t - window, t] — with
    fully-out-of-band blocks skipped in-kernel, so banded attention
    costs O(S·window).  Gradients flow to q/k/v only (masks are data).

    ``dropout_p`` > 0 drops attention probabilities IN-KERNEL (the
    reference's fused-dropout feature, apex/contrib/csrc/multihead_attn/
    dropout.cuh): the mask is a counter-based hash of (``dropout_seed``,
    head, row, col) regenerated in the backward — no (Sq, Sk) mask
    tensor ever exists in HBM.  The XLA fallback applies the identical
    hash mask, so dispatch does not change numerics for a given seed.
    """
    if sliding_window is not None:
        if not causal:
            raise ValueError(
                "sliding_window requires causal=True (the band is "
                "defined against the causal direction)")
        if sliding_window < 1:
            raise ValueError(
                f"sliding_window must be >= 1, got {sliding_window}")
    if dropout_p:
        if not 0.0 <= dropout_p < 1.0:
            raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed (an "
                             "int32 scalar; derive one per step from the "
                             "training PRNG key)")
    if scale is None:
        scale = 1.0 / math.sqrt(q4.shape[-1])
    mode = pallas_mode()
    # dispatch policy: the registered probe encodes the measured
    # crossover (min-sk boundary + score-byte cap) and a ledger entry
    # for this chip/shape overrides it; the decision is trace-time
    # static and lands in the observe event log (kernels.dispatch)
    from ...kernels.dispatch import attention_fp, decide
    b, h, sq, d = q4.shape
    tier = decide("flash_attention",
                  attention_fp(b, h, sq, k4.shape[2], d, q4.dtype,
                               causal)).tier
    if mode is None or tier == "xla":
        if bias is not None:
            bias = jax.lax.stop_gradient(bias)
        return attention_reference(q4, k4, v4, bias, causal, scale,
                                   window=sliding_window,
                                   dropout_p=dropout_p,
                                   dropout_seed=dropout_seed)
    return _flash(q4, k4, v4, bias,
                  None if not dropout_p else dropout_seed,
                  causal, scale, mode == "interpret", sliding_window,
                  dropout_p)


# ---------------------------------------------------------------------------
# reference-parity functional paths (torch layout: inputs (T, B, E))
# ---------------------------------------------------------------------------

def _split_interleaved_qkv(lin, t, b, heads, head_dim):
    """(T, B, 3E) → three (B·H, T, D), reference interleaved slicing
    (self_multihead_attn_func.py:35-38)."""
    lin = lin.reshape(t, b * heads, 3, head_dim)
    q, k, v = lin[:, :, 0], lin[:, :, 1], lin[:, :, 2]
    to_bhd = lambda x: jnp.swapaxes(x, 0, 1)  # (BH, T, D)
    return to_bhd(q), to_bhd(k), to_bhd(v)


def _masks_to_bias(mask, use_time_mask, b, heads, sq, sk, dtype=_f32):
    """Reference mask semantics → additive bias (B|1, Sq|1, Sk).

    Boolean/byte masks mark EXCLUDED positions with True
    (self_multihead_attn_func.py:52-66); float masks are additive."""
    if mask is None:
        return None
    mask = jnp.asarray(mask)
    if use_time_mask:
        assert mask.ndim == 2, "Timing mask is not 2D!"
        if mask.dtype == jnp.bool_ or jnp.issubdtype(mask.dtype, jnp.integer):
            return jnp.where(mask.astype(bool), _NEG, 0.0).astype(
                _f32)[None, :, :]
        return mask.astype(_f32)[None, :, :]
    # key padding (B, Sk)
    if mask.dtype == jnp.bool_ or jnp.issubdtype(mask.dtype, jnp.integer):
        return jnp.where(mask.astype(bool), _NEG, 0.0).astype(
            _f32)[:, None, :]
    return mask.astype(_f32)[:, None, :]


def _dropout_seed(key, tp_axis=None):
    """int32 kernel seed from the step's PRNG key.  Under TP the mesh
    axis index folds in, so each head-shard draws a decorrelated mask
    stream (the reference's per-rank Philox-stream semantics: multi-rank
    dropout is statistically independent, not bitwise equal to the
    single-device run)."""
    seed = jax.random.bits(key, dtype=jnp.uint32)
    if tp_axis is not None:
        seed = seed ^ (jax.lax.axis_index(tp_axis).astype(jnp.uint32)
                       * jnp.uint32(0x9E3779B1))
    return seed.astype(jnp.int32)


def _attn_with_dropout(q3, k3, v3, bias, heads, scale, dropout_prob, key,
                       use_time_mask_causal=False):
    """Materializing attention with dropout on the probabilities — the
    default-impl math (self_multihead_attn_func.py:49-87)."""
    bh, sq, d = q3.shape
    b = bh // heads
    s = jnp.einsum("btd,bsd->bts", q3.astype(_f32),
                   k3.astype(_f32)) * scale
    if bias is not None:
        s = s.reshape(b, heads, sq, -1) + bias[:, None].astype(_f32)
        s = s.reshape(bh, sq, -1)
    if use_time_mask_causal:
        rows = jnp.arange(sq)[:, None]
        cols = jnp.arange(s.shape[-1])[None, :]
        s = jnp.where(rows >= cols, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_prob > 0.0:
        if key is None:
            raise ValueError("attention dropout requires a PRNG key")
        keep = 1.0 - dropout_prob
        m = dropout_mask(key, keep, p.shape)
        p = jnp.where(m, p / keep, 0.0)
    return jnp.einsum("bts,bsd->btd", p, v3.astype(_f32)).astype(q3.dtype)


def self_attn_func(use_time_mask, is_training, heads, scale, inputs,
                   input_weights, output_weights, input_biases=None,
                   output_biases=None, mask=None, dropout_prob=0.0,
                   key=None, use_flash=False, causal=False,
                   seq_parallel_axis=None, seq_parallel_impl="ring",
                   tensor_parallel_axis=None, sp_shared_key=None):
    """Reference signature parity (self_multihead_attn_func.py:6-10);
    ``use_flash`` selects the Pallas path (the fast_* extension analogue).
    ``causal`` applies the triangle in-kernel (no O(S^2) mask operand) —
    beyond the reference signature, for decoder models.

    ``seq_parallel_axis``: run inside shard_map with the time dim sharded
    on that mesh axis — attention rides the ring (or Ulysses all-to-all,
    per ``seq_parallel_impl``) while projections stay local.  The causal
    triangle is handled globally by the SP kernels; masks are supported
    under 'ulysses' only (pass them GLOBAL-shape and replicated).
    Attention dropout composes with BOTH impls: ring hashes global
    coordinates under the replicated pre-shard key (bit-consistent with
    the single-device run), ulysses decorrelates per head-shard.

    ``tensor_parallel_axis``: Megatron-style head sharding over a mesh
    axis.  The QKV projection is column-parallel — the interleaved weight
    layout groups rows per head, so a contiguous row block IS a head
    block — each device attends over ``heads / n_tp`` local heads, and the
    output projection is row-parallel with the single psum of the
    column→row pattern (parallel/tensor_parallel.py).  Weights stay FULL
    (replicated); each device slices its block at trace time, which XLA
    folds into the weight layout.  Composes with ``seq_parallel_axis``
    (TP shards heads, SP shards time).  Attention dropout composes with
    TP on the flash path (per-shard seed streams, ``_dropout_seed``);
    the materializing 'default' impl refuses it under TP.
    """
    t, b, e = inputs.shape
    head_dim = e // heads
    iw, ow, ib = input_weights, output_weights, input_biases
    if tensor_parallel_axis is not None:
        # shared entry protocol (f operator on the stream, head check,
        # block slicing): rows of in_proj group [q_h, k_h, v_h] per head
        # (module docstring) so a contiguous row block is a head block;
        # out_proj contracts the heads-major context so column block i
        # multiplies exactly head block i
        from ...parallel.tensor_parallel import tp_attn_begin
        (inputs,), heads, rows, (ow,) = tp_attn_begin(
            tensor_parallel_axis, heads,
            [inputs], [iw] + ([ib] if ib is not None else []), [ow])
        iw = rows[0]
        if ib is not None:
            ib = rows[1]
        e = heads * head_dim
    lin = jnp.matmul(inputs, iw.T)
    if ib is not None:
        lin = lin + ib
    q3, k3, v3 = _split_interleaved_qkv(lin, t, b, heads, head_dim)
    dropout = dropout_prob if is_training else 0.0
    if seq_parallel_axis is not None:
        from ...parallel.ring_attention import (ring_attention,
                                                ulysses_attention)
        if seq_parallel_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_parallel_impl must be 'ring' or 'ulysses', got "
                f"{seq_parallel_impl!r}")
        sp_bias = None
        if mask is not None:
            if seq_parallel_impl != "ulysses":
                raise NotImplementedError(
                    "masks under sequence parallelism require the "
                    "'ulysses' impl (each device sees the gathered global "
                    "sequence there; the ring carries no mask operand)")
            # the mask must be GLOBAL (key_padding (B, S_global) or time
            # (S_g, S_g)) and replicated across the axis; the bias derives
            # from the mask's own (global) shape, and ulysses_attention
            # validates it against the gathered lengths
            sp_bias = _masks_to_bias(mask, use_time_mask, b, heads, t, t)
        ring_seed = uly_seed = None
        if dropout > 0.0:
            # ring: the mask hashes GLOBAL coordinates, so a seed
            # replicated across the axis makes SP dropout bit-consistent
            # with the single-device kernel.  ulysses: heads are what is
            # sharded — per-shard decorrelated streams (TP semantics).
            if seq_parallel_impl == "ring":
                if sp_shared_key is None:
                    raise ValueError(
                        "ring-SP attention dropout needs the replicated "
                        "pre-shard key (sp_shared_key); model forwards "
                        "supply it via fold_shard_into_key's shared_key")
                # sp-replicated seed; under a TP x SP mesh the tp fold
                # decorrelates head shards (axis_index(tp) is constant
                # along sp, so sp-replication survives)
                ring_seed = _dropout_seed(sp_shared_key,
                                          tensor_parallel_axis)
            else:
                if key is None:
                    raise ValueError(
                        "attention dropout requires a PRNG key")
                uly_seed = _dropout_seed(key, tensor_parallel_axis)
        q4 = q3.reshape(b, heads, t, head_dim)
        k4 = k3.reshape(b, heads, t, head_dim)
        v4 = v3.reshape(b, heads, t, head_dim)
        if seq_parallel_impl == "ring":
            ctx4 = ring_attention(q4, k4, v4,
                                  axis_name=seq_parallel_axis,
                                  causal=causal, scale=scale,
                                  dropout_p=dropout,
                                  dropout_seed=ring_seed)
        else:
            ctx4 = ulysses_attention(q4, k4, v4,
                                     axis_name=seq_parallel_axis,
                                     causal=causal, scale=scale,
                                     bias=sp_bias, dropout_p=dropout,
                                     dropout_seed=uly_seed)
        ctx3 = ctx4.reshape(b * heads, t, head_dim)
    elif use_flash:
        # dropout rides IN-KERNEL (the reference fast path fuses dropout
        # the same way, apex/contrib/csrc/multihead_attn/dropout.cuh)
        bias = _masks_to_bias(mask, use_time_mask, b, heads, t, t)
        q4 = q3.reshape(b, heads, t, head_dim)
        k4 = k3.reshape(b, heads, t, head_dim)
        v4 = v3.reshape(b, heads, t, head_dim)
        seed = None
        if dropout > 0.0:
            if key is None:
                raise ValueError("attention dropout requires a PRNG key")
            seed = _dropout_seed(key, tensor_parallel_axis)
        ctx4 = flash_attention(q4, k4, v4, bias=bias, causal=causal,
                               scale=scale, dropout_p=dropout,
                               dropout_seed=seed)
        ctx3 = ctx4.reshape(b * heads, t, head_dim)
    else:
        if tensor_parallel_axis is not None and dropout > 0.0:
            raise NotImplementedError(
                "attention dropout under tensor parallelism requires the "
                "flash path (impl='fast'): the materializing impl draws "
                "its mask from one shared key, which would correlate "
                "dropout across head shards")
        bias = _masks_to_bias(mask, use_time_mask, b, heads, t, t)
        ctx3 = _attn_with_dropout(q3, k3, v3, bias, heads, scale, dropout,
                                  key, use_time_mask_causal=causal)
    ctx = jnp.swapaxes(ctx3, 0, 1).reshape(t, b, e)
    out = jnp.matmul(ctx, ow.T)
    if tensor_parallel_axis is not None:
        # the row-parallel reduction (Megatron g: psum fwd, identity
        # bwd): one collective for the whole column→row attention pair;
        # bias added once, after the reduction
        from ...parallel.tensor_parallel import reduce_from_tp_region
        out = reduce_from_tp_region(out, tensor_parallel_axis)
    if output_biases is not None:
        out = out + output_biases
    return out


def encdec_attn_func(use_time_mask, is_training, heads, scale, inputs_q,
                     inputs_kv, input_weights_q, input_weights_kv,
                     output_weights, mask=None, dropout_prob=0.0,
                     key=None, use_flash=False,
                     tensor_parallel_axis=None):
    """Encoder-decoder attention (encdec_multihead_attn_func.py): q from the
    decoder stream, interleaved (k, v) from the encoder stream.

    ``tensor_parallel_axis``: Megatron head sharding, same design as
    ``self_attn_func`` — q rows group per head and kv rows per head as
    ``[k_h, v_h]`` pairs, so contiguous row blocks are head blocks; the
    output projection is row-parallel with one reduction.  Both streams
    pass through the f operator (their gradients feed the encoder AND
    decoder stacks)."""
    tq, b, e = inputs_q.shape
    tk = inputs_kv.shape[0]
    head_dim = e // heads
    wq, wkv, ow = input_weights_q, input_weights_kv, output_weights
    if tensor_parallel_axis is not None:
        # shared entry protocol; q rows group per head, kv rows per head
        # as [k_h, v_h] pairs — contiguous row blocks are head blocks
        from ...parallel.tensor_parallel import tp_attn_begin
        (inputs_q, inputs_kv), heads, (wq, wkv), (ow,) = tp_attn_begin(
            tensor_parallel_axis, heads,
            [inputs_q, inputs_kv], [wq, wkv], [ow])
        e = heads * head_dim
    q = jnp.matmul(inputs_q, wq.T)
    kv = jnp.matmul(inputs_kv, wkv.T)
    q3 = jnp.swapaxes(q.reshape(tq, b * heads, head_dim), 0, 1)
    kv = kv.reshape(tk, b * heads, 2, head_dim)
    k3 = jnp.swapaxes(kv[:, :, 0], 0, 1)
    v3 = jnp.swapaxes(kv[:, :, 1], 0, 1)
    bias = _masks_to_bias(mask, use_time_mask, b, heads, tq, tk)
    dropout = dropout_prob if is_training else 0.0
    if use_flash:
        q4 = q3.reshape(b, heads, tq, head_dim)
        k4 = k3.reshape(b, heads, tk, head_dim)
        v4 = v3.reshape(b, heads, tk, head_dim)
        seed = None
        if dropout > 0.0:
            # in-kernel dropout, same contract as self_attn_func
            if key is None:
                raise ValueError("attention dropout requires a PRNG key")
            seed = _dropout_seed(key, tensor_parallel_axis)
        ctx4 = flash_attention(q4, k4, v4, bias=bias, causal=False,
                               scale=scale, dropout_p=dropout,
                               dropout_seed=seed)
        ctx3 = ctx4.reshape(b * heads, tq, head_dim)
    else:
        if tensor_parallel_axis is not None and dropout > 0.0:
            raise NotImplementedError(
                "attention dropout under tensor parallelism requires the "
                "flash path (impl='fast'): the materializing impl draws "
                "its mask from one shared key, which would correlate "
                "dropout across head shards")
        ctx3 = _attn_with_dropout(q3, k3, v3, bias, heads, scale, dropout,
                                  key)
    ctx = jnp.swapaxes(ctx3, 0, 1).reshape(tq, b, e)
    out = jnp.matmul(ctx, ow.T)
    if tensor_parallel_axis is not None:
        from ...parallel.tensor_parallel import reduce_from_tp_region
        out = reduce_from_tp_region(out, tensor_parallel_axis)
    return out
