"""apex.contrib equivalents.  Subpackages import lazily like the reference
(extensions are opt-in there, setup.py:37-296):

    from apex_tpu.contrib import xentropy, multihead_attn, groupbn, optimizers
"""
from . import groupbn, multihead_attn, optimizers, xentropy  # noqa: F401
