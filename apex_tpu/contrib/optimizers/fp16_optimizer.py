"""Contrib FP16_Optimizer — the cut-down master-weight wrapper (reference:
apex/contrib/optimizers/fp16_optimizer.py) designed specifically for the
contrib fused optimizers: it keeps fp32 masters swapped into the inner
``param_groups`` and drives the legacy ``step(grads=…, output_params=…,
scale=…)`` surface so the inner optimizer performs unscale + master update +
half-weight write-out in one fused pass (the fp16_utils version instead
copies grads/params around the step, fp16_optimizer.py:142-186 there).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...fp16_utils.loss_scaler import DynamicLossScaler, LossScaler
from ...nn.parameter import Parameter

_HALF = (jnp.float16, jnp.bfloat16)


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=True):
        self.optimizer = init_optimizer
        self.verbose = verbose
        self.fp16_groups = []   # model (half) params
        self.fp32_groups = []   # master weights
        for group in self.optimizer.param_groups:
            fp16, fp32 = [], []
            for p in group["params"]:
                fp16.append(p)
                master = Parameter(p.data.astype(jnp.float32))
                master.requires_grad = True
                fp32.append(master)
            self.fp16_groups.append(fp16)
            self.fp32_groups.append(fp32)
            group["params"] = fp32

        if dynamic_loss_scale:
            self.dynamic_loss_scale = True
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.dynamic_loss_scale = False
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False

    # -- reference API ----------------------------------------------------
    def zero_grad(self, set_grads_to_None=True):
        for group in self.fp16_groups:
            for p in group:
                p.grad = None if set_grads_to_None else \
                    jnp.zeros_like(p.data)

    def backward(self, loss, update_master_grads=True):
        """Scaled backward through the tape (reference :105-116 defers to
        amp-era loss.backward with scale folded in); grads land on the fp16
        model params."""
        self.loss_scaler.backward(loss)

    def step(self, closure=None):
        if closure is not None:
            raise RuntimeError(
                "contrib FP16_Optimizer does not support closures")
        model_params = [p for g in self.fp16_groups for p in g]
        grads = [[p.grad for p in g] for g in self.fp16_groups]
        self.overflow = bool(self.loss_scaler.has_overflow(model_params))
        if self.overflow:
            # overflow path updates the scale FIRST (halve) and skips
            self.loss_scaler.update_scale(True)
            if self.verbose:
                print(f"OVERFLOW! Skipping step. Reducing loss scale to "
                      f"{self.loss_scaler.loss_scale}")
            return
        # per-group norms of the (still-scaled) grads, forwarded so the
        # inner optimizer's max_grad_norm clip works (the reference wrapper
        # computes these in the same pass as its overflow check)
        grad_norms = [
            float(jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2)
                               for g in gg if g is not None)))
            if any(g is not None for g in gg) else None
            for gg in grads]
        self.optimizer.step(grads=grads,
                            output_params=self.fp16_groups,
                            scale=self.loss_scaler.loss_scale,
                            grad_norms=grad_norms)
        # grow-after-window happens AFTER the step so the unscale uses the
        # same scale the backward applied
        self.loss_scaler.update_scale(False)

    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale

    @property
    def param_groups(self):
        return self.optimizer.param_groups

    def state_dict(self):
        import copy
        # snapshot, not a live reference: the reference stores the mutable
        # scaler object itself, so a held checkpoint dict keeps mutating as
        # training continues (cur_scale/cur_iter) until pickled
        return {
            "loss_scaler": copy.deepcopy(self.loss_scaler),
            "dynamic_loss_scale": self.dynamic_loss_scale,
            "overflow": self.overflow,
            "optimizer_state_dict": self.optimizer.state_dict(),
            "fp32_groups": [[p.data for p in g] for g in self.fp32_groups],
        }

    def load_state_dict(self, state_dict):
        import copy
        # adopt a copy, not the checkpoint's object (same aliasing bug as
        # state_dict, on the load side)
        self.loss_scaler = copy.deepcopy(state_dict["loss_scaler"])
        self.dynamic_loss_scale = state_dict["dynamic_loss_scale"]
        self.overflow = state_dict["overflow"]
        self.optimizer.load_state_dict(state_dict["optimizer_state_dict"])
        for group, saved in zip(self.fp32_groups, state_dict["fp32_groups"]):
            for p, d in zip(group, saved):
                p.data = jnp.asarray(d)
        for m_group, f_group in zip(self.fp16_groups, self.fp32_groups):
            for m, f in zip(m_group, f_group):
                m.data = f.data.astype(m.data.dtype)
