"""Contrib FusedLAMB — the older two-stage LAMB pipeline (reference:
apex/contrib/optimizers/fused_lamb.py driving
csrc/multi_tensor_lamb_stage_1.cu and _stage_2.cu).

Stage 1 per tensor: moment updates + Adam-style step direction ``u`` with
the *per-tensor* grad norm divided out of the decay term and the global
clip folded into the grad scale.  Stage 2: trust-ratio apply
``p -= lr · (‖p‖/‖u‖) · u``.  Kept as two jitted passes (with the
per-tensor norms between them) to mirror the observable two-call structure;
XLA fuses each pass across the group.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ... import ops
from ...multi_tensor_apply import multi_tensor_applier
from ...optimizers.base import Optimizer, split_by_dtype

_f32 = jnp.float32


@functools.partial(jax.jit, static_argnames=(
    "beta1", "beta2", "eps", "bias_correction", "weight_decay",
    "grad_averaging"))
def _stage1(grads, params, ms, vs, step, clip_scale, beta1, beta2, eps,
            bias_correction, weight_decay, grad_averaging):
    """→ (new_m, new_v, updates u)."""
    beta3 = (1 - beta1) if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - beta1 ** step.astype(_f32)
        bc2 = 1.0 - beta2 ** step.astype(_f32)
    else:
        bc1 = bc2 = jnp.asarray(1.0, _f32)
    new_m, new_v, us = [], [], []
    for g, p, m, v in zip(grads, params, ms, vs):
        gf = g.astype(_f32) * clip_scale
        m = beta1 * m + beta3 * gf
        v = beta2 * v + (1 - beta2) * gf * gf
        u = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + \
            weight_decay * p.astype(_f32)
        new_m.append(m)
        new_v.append(v)
        us.append(u)
    return new_m, new_v, us


@jax.jit
def _stage2(params, us, lr):
    """Trust-ratio apply (csrc/multi_tensor_lamb_stage_2.cu): per-tensor
    ``ratio = ‖p‖/‖u‖`` (1 where either norm is 0)."""
    new_p = []
    for p, u in zip(params, us):
        pf = p.astype(_f32)
        pn = jnp.sqrt(jnp.sum(pf * pf))
        un = jnp.sqrt(jnp.sum(u * u))
        ratio = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
        new_p.append((pf - lr * ratio * u).astype(p.dtype))
    return new_p


class FusedLAMB(Optimizer):
    """Two-stage LAMB (contrib surface; the modern single-call version is
    apex_tpu.optimizers.FusedLAMB)."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0):
        if amsgrad:
            raise RuntimeError(
                "FusedLAMB does not support the AMSGrad variant.")
        if not adam_w_mode:
            raise RuntimeError(
                "contrib FusedLAMB only supports adam_w_mode (decoupled "
                "decay), matching the stage-1 kernel")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        grad_averaging=grad_averaging,
                        max_grad_norm=max_grad_norm)
        super().__init__(params, defaults)
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def zero_grad(self, set_to_none=None):
        super().zero_grad(self.set_grad_none if set_to_none is None
                          else set_to_none)

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        # global grad norm across every group/dtype (fused_lamb.py:106-125)
        all_grads = [p.grad for g in self.param_groups for p in g["params"]
                     if p.grad is not None]
        if not all_grads:
            return loss
        _, gnorm, _ = multi_tensor_applier(
            ops.multi_tensor_l2norm, self._overflow_buf, [all_grads], False)

        for group in self.param_groups:
            plist = [p for p in group["params"] if p.grad is not None]
            if not plist:
                continue
            group["step"] = group.get("step", 0) + 1
            beta1, beta2 = group["betas"]
            max_norm = group["max_grad_norm"]
            clip = jnp.where(gnorm > max_norm, max_norm / gnorm, 1.0) \
                if max_norm > 0 else jnp.asarray(1.0, _f32)
            for dtype, sub in split_by_dtype(plist).items():
                for p in sub:
                    st = self.state[p]
                    if len(st) == 0:
                        st["exp_avg"] = jnp.zeros(p.data.shape, _f32)
                        st["exp_avg_sq"] = jnp.zeros(p.data.shape, _f32)
                new_m, new_v, us = _stage1(
                    [p.grad for p in sub], [p.data for p in sub],
                    [self.state[p]["exp_avg"] for p in sub],
                    [self.state[p]["exp_avg_sq"] for p in sub],
                    jnp.asarray(group["step"], jnp.int32), clip,
                    beta1, beta2, group["eps"],
                    bool(group["bias_correction"]), group["weight_decay"],
                    bool(group["grad_averaging"]))
                new_p = _stage2([p.data for p in sub], us,
                                jnp.asarray(group["lr"], _f32))
                for p, np_, nm, nv in zip(sub, new_p, new_m, new_v):
                    p.data = np_
                    self.state[p]["exp_avg"] = nm
                    self.state[p]["exp_avg_sq"] = nv
        return loss
