"""Contrib FusedLAMB — the older two-stage LAMB pipeline (reference:
apex/contrib/optimizers/fused_lamb.py driving
csrc/multi_tensor_lamb_stage_1.cu and _stage_2.cu).

Stage 1 per tensor: moment updates + Adam-style step direction ``u`` with
the *per-tensor* grad norm divided out of the decay term and the global
clip folded into the grad scale.  Stage 2: trust-ratio apply
``p -= lr · (‖p‖/‖u‖) · u``.  Both stages — plus the global grad norm that
feeds the clip — now compile into ONE step-cache executable per optimizer
with traced hyperparameters and donated params/moments (the observable
two-call structure of the reference collapses the way its two kernels would
under XLA fusion anyway).
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import ops
from ...optimizers.base import (Optimizer, dispatch_cached_step,
                                split_by_dtype)

_f32 = jnp.float32


def _contrib_lamb_update(static_cfg, donated, grads, hyper, flag):
    """Pure whole-optimizer two-stage LAMB update: global grad norm →
    per-group clip → stage 1 (moments + u) → stage 2 (trust-ratio apply)."""
    bias_corrections, grad_avgs, max_norms = static_cfg
    all_grads = [g for gs in grads for g in gs]
    _, gnorm, _ = ops.multi_tensor_l2norm(flag, [all_grads])
    new_steps = [s + 1 for s in donated["steps"]]
    new_groups = []
    for entry, gs, h, bc, ga, max_norm, step in zip(
            donated["groups"], grads, hyper, bias_corrections, grad_avgs,
            max_norms, new_steps):
        clip = jnp.where(gnorm > max_norm, max_norm / gnorm, 1.0) \
            if max_norm > 0 else jnp.asarray(1.0, _f32)
        beta3 = (1 - h["beta1"]) if ga else jnp.asarray(1.0, _f32)
        if bc:
            bc1 = 1.0 - h["beta1"] ** step.astype(_f32)
            bc2 = 1.0 - h["beta2"] ** step.astype(_f32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, _f32)
        new_p, new_m, new_v = [], [], []
        for g, p, m, v in zip(gs, entry["p"], entry["m"], entry["v"]):
            gf = g.astype(_f32) * clip
            mf = h["beta1"] * m + beta3 * gf
            vf = h["beta2"] * v + (1 - h["beta2"]) * gf * gf
            pf = p.astype(_f32)
            u = (mf / bc1) / (jnp.sqrt(vf / bc2) + h["eps"]) + \
                h["weight_decay"] * pf
            # stage 2 (csrc/multi_tensor_lamb_stage_2.cu): per-tensor
            # ratio = ‖p‖/‖u‖, 1 where either norm is 0
            pn = jnp.sqrt(jnp.sum(pf * pf))
            un = jnp.sqrt(jnp.sum(u * u))
            ratio = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
            new_p.append((pf - h["lr"] * ratio * u).astype(p.dtype))
            new_m.append(mf)
            new_v.append(vf)
        new_groups.append({"p": new_p, "m": new_m, "v": new_v})
    return {"steps": new_steps, "groups": new_groups}


class FusedLAMB(Optimizer):
    """Two-stage LAMB (contrib surface; the modern single-call version is
    apex_tpu.optimizers.FusedLAMB)."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0):
        if amsgrad:
            raise RuntimeError(
                "FusedLAMB does not support the AMSGrad variant.")
        if not adam_w_mode:
            raise RuntimeError(
                "contrib FusedLAMB only supports adam_w_mode (decoupled "
                "decay), matching the stage-1 kernel")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        grad_averaging=grad_averaging,
                        max_grad_norm=max_grad_norm)
        super().__init__(params, defaults)
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        live_groups = []
        for group in self.param_groups:
            plist = [p for p in group["params"] if p.grad is not None]
            if not plist:
                continue
            # dtype split kept for state-init order parity; stage math is
            # fp32 regardless of storage dtype so the update itself is flat
            for sub in split_by_dtype(plist).values():
                for p in sub:
                    st = self.state[p]
                    if len(st) == 0:
                        st["exp_avg"] = jnp.zeros(p.data.shape, _f32)
                        st["exp_avg_sq"] = jnp.zeros(p.data.shape, _f32)
            live_groups.append((group, plist))
        if not live_groups:
            return loss

        donated = {"steps": [jnp.asarray(g.get("step", 0), jnp.int32)
                             for g, _ in live_groups],
                   "groups": []}
        grads_tree, hyper = [], []
        for group, plist in live_groups:
            beta1, beta2 = group["betas"]
            donated["groups"].append({
                "p": [p.data for p in plist],
                "m": [self.state[p]["exp_avg"] for p in plist],
                "v": [self.state[p]["exp_avg_sq"] for p in plist]})
            grads_tree.append([p.grad for p in plist])
            hyper.append({
                "lr": jnp.asarray(group["lr"], _f32),
                "beta1": jnp.asarray(beta1, _f32),
                "beta2": jnp.asarray(beta2, _f32),
                "eps": jnp.asarray(group["eps"], _f32),
                "weight_decay": jnp.asarray(group["weight_decay"], _f32)})

        static_cfg = (tuple(bool(g["bias_correction"])
                            for g, _ in live_groups),
                      tuple(bool(g["grad_averaging"]) for g, _ in live_groups),
                      tuple(g["max_grad_norm"] for g, _ in live_groups))
        new = dispatch_cached_step(self, "contrib_fused_lamb", static_cfg,
                                   _contrib_lamb_update, donated, grads_tree,
                                   hyper)

        for (group, plist), entry, s in zip(live_groups, new["groups"],
                                            new["steps"]):
            group["step"] = s
            for i, p in enumerate(plist):
                p.data = entry["p"][i]
                self.state[p]["exp_avg"] = entry["m"][i]
                self.state[p]["exp_avg_sq"] = entry["v"][i]
        return loss
