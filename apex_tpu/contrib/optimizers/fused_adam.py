"""Deprecated-API FusedAdam (reference: apex/contrib/optimizers/fused_adam.py,
backed by apex/contrib/csrc/optimizers/fused_adam_cuda_kernel.cu).

The legacy surface the modern apex.optimizers.FusedAdam removed: ``step``
accepts explicit ``grads`` / ``output_params`` / ``scale`` / ``grad_norms``,
folds the amp unscale into the update (kernel takes the combined scale), and
writes a reduced-precision copy of the fresh weights in the same pass (the
``out_p`` the CUDA kernel fills).  Group-level ``max_grad_norm`` turns the
scale into ``clip*scale`` when the reported grad norm exceeds it
(fused_adam.py:118-124).  ``eps_inside_sqrt`` selects
``sqrt(v + eps)`` denominators (eps_mode 0) vs ``sqrt(v) + eps``
(fused_adam.py:27-29,63).

TPU shape: one jitted update over each param group; fp32 math regardless of
storage dtype; the half output copy is a cast in the same fused program, not
a second kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...optimizers.base import Optimizer

_f32 = jnp.float32


@functools.partial(jax.jit, static_argnames=(
    "beta1", "beta2", "eps", "eps_mode", "bias_correction", "weight_decay",
    "out_dtypes"))
def _adam_legacy_step(grads, params, ms, vs, steps, lr, combined_scale,
                      beta1, beta2, eps, eps_mode, bias_correction,
                      weight_decay, out_dtypes):
    new_p, new_m, new_v, outs = [], [], [], []
    for g, p, m, v, step, od in zip(grads, params, ms, vs, steps,
                                    out_dtypes):
        # bias correction is per-param: params can enter the live set at
        # different iterations (grad=None freezing), and each carries its
        # own state['step'] like the reference's per-tensor kernel calls
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(_f32)
            bc2 = 1.0 - beta2 ** step.astype(_f32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, _f32)
        gf = g.astype(_f32) / combined_scale
        pf = p.astype(_f32)
        m = beta1 * m.astype(_f32) + (1 - beta1) * gf
        v = beta2 * v.astype(_f32) + (1 - beta2) * gf * gf
        mhat = m / bc1
        vhat = v / bc2
        if eps_mode == 0:        # eps inside sqrt
            denom = jnp.sqrt(vhat + eps)
        else:
            denom = jnp.sqrt(vhat) + eps
        update = mhat / denom + weight_decay * pf
        pf = pf - lr * update
        new_p.append(pf.astype(p.dtype))
        new_m.append(m)
        new_v.append(v)
        # half write-out casts straight from fp32 to the OUTPUT's dtype —
        # no lossy f16 intermediate for bf16 outputs
        outs.append(pf.astype(od) if od is not None else None)
    return new_p, new_m, new_v, outs


class FusedAdam(Optimizer):
    """Legacy fused Adam with in-kernel unscale and half output copies."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0., max_grad_norm=0., amsgrad=False,
                 use_mt=False, amp_scale_adjustment=1.0):
        if amsgrad:
            raise RuntimeError(
                "FusedAdam does not support the AMSGrad variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        max_grad_norm=max_grad_norm)
        super().__init__(params, defaults)
        self.eps_mode = 0 if eps_inside_sqrt else 1
        self._amp_scale_adjustment = amp_scale_adjustment
        self._use_multi_tensor = use_mt  # recorded; batching is XLA's job

    def step(self, closure=None, grads=None, output_params=None, scale=1.,
             grad_norms=None):
        loss = closure() if closure is not None else None

        if hasattr(self, "_amp_stash"):
            grads = self._amp_stash.grads
            output_params = self._amp_stash.output_params
            scale = self._amp_stash.scale * self._amp_scale_adjustment
            grad_norms = self._amp_stash.grad_norms

        def per_group(x):
            if x is None:
                return [None] * len(self.param_groups)
            if not isinstance(x[0], (list, tuple)):
                return [list(x)]
            return [list(g) for g in x]

        grads_group = per_group(grads)
        output_group = per_group(output_params)
        norms = grad_norms if grad_norms is not None else \
            [None] * len(self.param_groups)

        for group, g_this, out_this, gnorm in zip(
                self.param_groups, grads_group, output_group, norms):
            params = group["params"]
            if g_this is None:
                g_this = [p.grad for p in params]
            if out_this is None:
                out_this = [None] * len(params)

            # combined scale: unscale + global-norm clip in one factor
            # (fused_adam.py:118-124; norm arrives pre-unscale, i.e. ×scale)
            combined_scale = scale
            if group["max_grad_norm"] > 0 and gnorm is not None:
                clip = ((float(gnorm) / scale) + 1e-6) / \
                    group["max_grad_norm"]
                if clip > 1:
                    combined_scale = clip * scale

            live = [(p, g, o) for p, g, o in zip(params, g_this, out_this)
                    if g is not None]
            if not live:
                continue
            for p, _, _ in live:
                st = self.state[p]
                if len(st) == 0:
                    st["step"] = 0
                    st["exp_avg"] = jnp.zeros(p.data.shape, _f32)
                    st["exp_avg_sq"] = jnp.zeros(p.data.shape, _f32)
                st["step"] += 1
            beta1, beta2 = group["betas"]
            out_dtypes = tuple(
                str(jnp.dtype(o.data.dtype)) if o is not None else None
                for _, _, o in live)
            new_p, new_m, new_v, outs = _adam_legacy_step(
                [g.data if hasattr(g, "data") else g for _, g, _ in live],
                [p.data for p, _, _ in live],
                [self.state[p]["exp_avg"] for p, _, _ in live],
                [self.state[p]["exp_avg_sq"] for p, _, _ in live],
                [jnp.asarray(self.state[p]["step"], jnp.int32)
                 for p, _, _ in live],
                jnp.asarray(group["lr"], _f32),
                jnp.asarray(combined_scale, _f32),
                beta1, beta2, group["eps"], self.eps_mode,
                bool(group["bias_correction"]), group["weight_decay"],
                out_dtypes)
            for (p, _, o), np_, nm, nv, op_ in zip(live, new_p, new_m,
                                                   new_v, outs):
                p.data = np_
                self.state[p]["exp_avg"] = nm
                self.state[p]["exp_avg_sq"] = nv
                if o is not None:
                    o.data = op_
        return loss
