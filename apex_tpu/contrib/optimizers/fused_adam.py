"""Deprecated-API FusedAdam (reference: apex/contrib/optimizers/fused_adam.py,
backed by apex/contrib/csrc/optimizers/fused_adam_cuda_kernel.cu).

The legacy surface the modern apex.optimizers.FusedAdam removed: ``step``
accepts explicit ``grads`` / ``output_params`` / ``scale`` / ``grad_norms``,
folds the amp unscale into the update (kernel takes the combined scale), and
writes a reduced-precision copy of the fresh weights in the same pass (the
``out_p`` the CUDA kernel fills).  Group-level ``max_grad_norm`` turns the
scale into ``clip*scale`` when the reported grad norm exceeds it
(fused_adam.py:118-124).  ``eps_inside_sqrt`` selects
``sqrt(v + eps)`` denominators (eps_mode 0) vs ``sqrt(v) + eps``
(fused_adam.py:27-29,63).

TPU shape: ONE step-cache executable per optimizer step covering every param
group, with traced scalar hyperparameters (lr/betas/eps/wd/scale schedules
never retrace) and params + both moments + the stale half output copies
donated; fp32 math regardless of storage dtype; the half output copy is a
cast in the same fused program, not a second kernel.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import ops
from ...optimizers.base import Optimizer, dispatch_cached_step

_f32 = jnp.float32


def _legacy_adam_update(static_cfg, donated, grads, hyper, flag):
    """Pure whole-optimizer legacy-Adam update (all groups, in-kernel
    unscale via combined_scale, per-param bias correction, half write-out)."""
    eps_mode, bias_corrections = static_cfg
    new = []
    for entry, gs, h, bias_correction in zip(donated, grads, hyper,
                                             bias_corrections):
        new_p, new_m, new_v, outs = [], [], [], []
        for i, (g, p, m, v) in enumerate(zip(gs, entry["p"], entry["m"],
                                             entry["v"])):
            # bias correction is per-param: params can enter the live set at
            # different iterations (grad=None freezing), and each carries
            # its own state['step'] like the reference's per-tensor calls
            if bias_correction:
                bc1 = 1.0 - h["beta1"] ** h["steps"][i].astype(_f32)
                bc2 = 1.0 - h["beta2"] ** h["steps"][i].astype(_f32)
            else:
                bc1 = bc2 = jnp.asarray(1.0, _f32)
            gf = g.astype(_f32) / h["combined_scale"]
            pf = p.astype(_f32)
            mf = h["beta1"] * m.astype(_f32) + (1 - h["beta1"]) * gf
            vf = h["beta2"] * v.astype(_f32) + (1 - h["beta2"]) * gf * gf
            mhat = mf / bc1
            vhat = vf / bc2
            if eps_mode == 0:        # eps inside sqrt
                denom = jnp.sqrt(vhat + h["eps"])
            else:
                denom = jnp.sqrt(vhat) + h["eps"]
            update = mhat / denom + h["weight_decay"] * pf
            pf = pf - h["lr"] * update
            new_p.append(pf.astype(p.dtype))
            new_m.append(mf)
            new_v.append(vf)
            # half write-out casts straight from fp32 to the OUTPUT's dtype
            # — no lossy f16 intermediate for bf16 outputs
            o = entry["out"][i]
            outs.append(pf.astype(o.dtype) if o is not None else None)
        new.append({"p": new_p, "m": new_m, "v": new_v, "out": outs})
    return new


class FusedAdam(Optimizer):
    """Legacy fused Adam with in-kernel unscale and half output copies."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0., max_grad_norm=0., amsgrad=False,
                 use_mt=False, amp_scale_adjustment=1.0):
        if amsgrad:
            raise RuntimeError(
                "FusedAdam does not support the AMSGrad variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        max_grad_norm=max_grad_norm)
        super().__init__(params, defaults)
        self.eps_mode = 0 if eps_inside_sqrt else 1
        self._amp_scale_adjustment = amp_scale_adjustment
        self._use_multi_tensor = use_mt  # recorded; batching is XLA's job
        self._overflow_buf = ops.zero_flag()

    def step(self, closure=None, grads=None, output_params=None, scale=1.,
             grad_norms=None):
        loss = closure() if closure is not None else None

        if hasattr(self, "_amp_stash"):
            grads = self._amp_stash.grads
            output_params = self._amp_stash.output_params
            scale = self._amp_stash.scale * self._amp_scale_adjustment
            grad_norms = self._amp_stash.grad_norms

        def per_group(x):
            if x is None:
                return [None] * len(self.param_groups)
            if not isinstance(x[0], (list, tuple)):
                return [list(x)]
            return [list(g) for g in x]

        grads_group = per_group(grads)
        output_group = per_group(output_params)
        norms = grad_norms if grad_norms is not None else \
            [None] * len(self.param_groups)

        live_groups = []
        donated, grads_tree, hyper = [], [], []
        for group, g_this, out_this, gnorm in zip(
                self.param_groups, grads_group, output_group, norms):
            params = group["params"]
            if g_this is None:
                g_this = [p.grad for p in params]
            if out_this is None:
                out_this = [None] * len(params)

            # combined scale: unscale + global-norm clip in one factor
            # (fused_adam.py:118-124; norm arrives pre-unscale, i.e. ×scale)
            combined_scale = scale
            if group["max_grad_norm"] > 0 and gnorm is not None:
                clip = ((float(gnorm) / scale) + 1e-6) / \
                    group["max_grad_norm"]
                if clip > 1:
                    combined_scale = clip * scale

            live = [(p, g, o) for p, g, o in zip(params, g_this, out_this)
                    if g is not None]
            if not live:
                continue
            for p, _, _ in live:
                st = self.state[p]
                if len(st) == 0:
                    st["step"] = 0
                    st["exp_avg"] = jnp.zeros(p.data.shape, _f32)
                    st["exp_avg_sq"] = jnp.zeros(p.data.shape, _f32)
                st["step"] += 1
            beta1, beta2 = group["betas"]
            live_groups.append((group, live))
            donated.append({
                "p": [p.data for p, _, _ in live],
                "m": [self.state[p]["exp_avg"] for p, _, _ in live],
                "v": [self.state[p]["exp_avg_sq"] for p, _, _ in live],
                "out": [None if o is None else o.data for _, _, o in live]})
            grads_tree.append([g.data if hasattr(g, "data") else g
                               for _, g, _ in live])
            hyper.append({
                "lr": jnp.asarray(group["lr"], _f32),
                "combined_scale": jnp.asarray(combined_scale, _f32),
                "beta1": jnp.asarray(beta1, _f32),
                "beta2": jnp.asarray(beta2, _f32),
                "eps": jnp.asarray(group["eps"], _f32),
                "weight_decay": jnp.asarray(group["weight_decay"], _f32),
                "steps": [jnp.asarray(self.state[p]["step"], jnp.int32)
                          for p, _, _ in live]})
        if not live_groups:
            return loss

        static_cfg = (self.eps_mode,
                      tuple(bool(g["bias_correction"])
                            for g, _ in live_groups))
        new = dispatch_cached_step(self, "contrib_fused_adam", static_cfg,
                                   _legacy_adam_update, donated, grads_tree,
                                   hyper)
        for (group, live), entry in zip(live_groups, new):
            for i, (p, _, o) in enumerate(live):
                p.data = entry["p"][i]
                self.state[p]["exp_avg"] = entry["m"][i]
                self.state[p]["exp_avg_sq"] = entry["v"][i]
                if o is not None:
                    o.data = entry["out"][i]
        return loss
