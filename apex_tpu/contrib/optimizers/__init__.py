"""apex_tpu.contrib.optimizers (reference: apex/contrib/optimizers/) —
the deprecated fused-optimizer surface: legacy-API FusedAdam (explicit
grads/output_params/scale step), two-stage FusedLAMB, and the cut-down
FP16_Optimizer built for them."""
from .fp16_optimizer import FP16_Optimizer  # noqa: F401
from .fused_adam import FusedAdam  # noqa: F401
from .fused_lamb import FusedLAMB  # noqa: F401

__all__ = ["FP16_Optimizer", "FusedAdam", "FusedLAMB"]
