"""apex.contrib.groupbn equivalent (reference apex/contrib/groupbn/__init__.py)."""
from .batch_norm import BatchNorm2d_NHWC  # noqa: F401
