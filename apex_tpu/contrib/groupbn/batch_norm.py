"""Group BatchNorm, NHWC — TPU-native equivalent of
``apex.contrib.groupbn.BatchNorm2d_NHWC``
(apex/contrib/groupbn/batch_norm.py:101-219 over the ``bnp`` extension,
apex/contrib/csrc/groupbn/ — NHWC BN with optional add+ReLU fusion and
cross-GPU group statistics over CUDA IPC peer memory).

TPU stance: NHWC is just the channel-last layout XLA already prefers on TPU;
the IPC peer-memory machinery disappears — ``bn_group`` maps to
``axis_index_groups`` sub-groups of the mesh's data axis and the stat
exchange is a sub-axis collective over ICI (SURVEY.md §2.2 bnp row).  The
fused ``add+relu`` epilogue (bn_addrelu_*) is the ``z``/``fuse_relu``
arguments; XLA fuses the elementwise tail into the surrounding step.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...nn import functional as F
from ...nn.modules import Buffer, Module
from ...nn.parameter import Parameter
from ...parallel import create_syncbn_process_group


class BatchNorm2d_NHWC(Module):
    """BatchNorm over NHWC input (stats on the last axis).

    ``bn_group`` > 1 synchronizes statistics across groups of ``bn_group``
    devices along the mesh data axis (the reference's intra-node IPC group,
    batch_norm.py:113-137); ``fuse_relu`` applies ReLU to the output and
    ``forward(x, z)`` fuses a residual add first (bn_addrelu path).
    """

    def __init__(self, num_features, fuse_relu=False, bn_group=1,
                 max_cta_per_sm=2, cta_launch_margin=12, multi_stream=False,
                 eps=1e-5, momentum=0.1, axis_name="data",
                 group_world_size=None):
        super().__init__()
        # max_cta_per_sm / cta_launch_margin / multi_stream are CUDA launch
        # tuning knobs (batch_norm.py:103); accepted for API parity
        self.num_features = num_features
        self.fuse_relu = fuse_relu
        self.bn_group = bn_group
        self.eps = eps
        self.momentum = momentum
        self.axis_name = axis_name if bn_group > 1 else None
        self.axis_index_groups = (
            create_syncbn_process_group(bn_group, group_world_size)
            if bn_group > 1 else None)
        self.weight = Parameter(jnp.ones((num_features,), jnp.float32))
        self.bias = Parameter(jnp.zeros((num_features,), jnp.float32))
        self.running_mean = Buffer(jnp.zeros((num_features,), jnp.float32))
        self.running_var = Buffer(jnp.ones((num_features,), jnp.float32))
        self.minibatch_mean = Buffer(jnp.zeros((num_features,), jnp.float32))
        self.minibatch_riv = Buffer(jnp.ones((num_features,), jnp.float32))

    def forward(self, ctx, x, z=None):
        training = ctx.training and self.training
        # NHWC natively: the shared stats core takes the channel axis
        # directly (channel_axis=-1) — no layout-transpose sandwich
        y, new_rm, new_rv, mb_mean, mb_riv = F.batch_norm(
            x, ctx.value(self.running_mean), ctx.value(self.running_var),
            ctx.value(self.weight), ctx.value(self.bias),
            training=training, momentum=self.momentum, eps=self.eps,
            axis_name=self.axis_name, channel_axis=-1,
            axis_index_groups=self.axis_index_groups, return_stats=True)
        if training:
            ctx.write_stat(self.running_mean, new_rm)
            ctx.write_stat(self.running_var, new_rv)
            ctx.write_stat(self.minibatch_mean, mb_mean)
            ctx.write_stat(self.minibatch_riv, mb_riv)
        if z is not None:
            y = y + z
        if self.fuse_relu:
            y = F.relu(y)
        return y

    def extra_repr(self):
        return (f"{self.num_features}, fuse_relu={self.fuse_relu}, "
                f"bn_group={self.bn_group}")
