"""apex.contrib.xentropy equivalent (reference apex/contrib/xentropy/__init__.py)."""
from .softmax_xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
