"""apex.contrib.xentropy equivalent (reference apex/contrib/xentropy/__init__.py)."""
from .chunked import (  # noqa: F401
    chunked_lm_head_loss,
    make_chunked_lm_loss,
)
from .softmax_xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
