"""Label-smoothed softmax cross-entropy with max_log_sum_exp residual —
TPU-native equivalent of ``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
(apex/contrib/xentropy/softmax_xentropy.py:4-28 over ``xentropy_cuda``,
apex/contrib/csrc/xentropy/xentropy_kernel.cu).

The extension's point is memory: the forward saves only the per-row
``max_log_sum_exp`` (one scalar per sample) instead of the full softmax; the
backward reconstructs probabilities as ``exp(logit - lse)``
(xentropy_kernel.cu:428-432: grad = softmax - ((1-s)·onehot + s/C)).  The
same residual contract here via ``jax.custom_vjp``.

Loss semantics (xentropy_kernel.cu:404-410): with smoothing s and C classes,
``loss_i = lse_i - (1-s)·logit_i[y_i] - s·mean_j(logit_ij)`` — i.e. cross
entropy against ``q = (1-s)·onehot + s/C``.  Per-sample losses are returned
(no reduction); rows with ``label == padding_idx`` contribute zero loss and
zero gradient (softmax_xentropy.py:10,24).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

_f32 = jnp.float32


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def softmax_cross_entropy_loss(logits, labels, smoothing=0.0, padding_idx=0,
                               half_to_float=False):
    losses, _ = _fwd_math(logits, labels, smoothing, padding_idx)
    if not half_to_float:
        losses = losses.astype(logits.dtype)
    return losses


def _fwd_math(logits, labels, smoothing, padding_idx):
    lf = logits.astype(_f32)
    m = jnp.max(lf, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
    tgt_logit = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    losses = lse - (1.0 - smoothing) * tgt_logit \
        - smoothing * jnp.mean(lf, axis=-1)
    losses = jnp.where(labels == padding_idx, 0.0, losses)
    return losses, lse


def _fwd(logits, labels, smoothing, padding_idx, half_to_float):
    losses, lse = _fwd_math(logits, labels, smoothing, padding_idx)
    out = losses if half_to_float else losses.astype(logits.dtype)
    # residual: logits + one scalar per row — NOT the softmax
    return out, (logits, lse, labels)


def _bwd(smoothing, padding_idx, half_to_float, res, g):
    logits, lse, labels = res
    c = logits.shape[-1]
    probs = jnp.exp(logits.astype(_f32) - lse[..., None])
    onehot = jax.nn.one_hot(labels, c, dtype=_f32)
    q = (1.0 - smoothing) * onehot + smoothing / c
    gmask = jnp.where(labels == padding_idx, 0.0, g.astype(_f32))
    grad = gmask[..., None] * (probs - q)
    return grad.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_fwd, _bwd)


class SoftmaxCrossEntropyLoss:
    """Reference-parity callable surface: the reference exposes a
    ``torch.autograd.Function`` used as ``SoftmaxCrossEntropyLoss.apply(...)``
    (softmax_xentropy.py:4)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          padding_idx, half_to_float)
