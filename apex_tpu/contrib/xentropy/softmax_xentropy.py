"""Label-smoothed softmax cross-entropy with max_log_sum_exp residual —
TPU-native equivalent of ``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
(apex/contrib/xentropy/softmax_xentropy.py:4-28 over ``xentropy_cuda``,
apex/contrib/csrc/xentropy/xentropy_kernel.cu).

The extension's point is memory: the forward saves only the per-row
``max_log_sum_exp`` (one scalar per sample) instead of the full softmax; the
backward reconstructs probabilities as ``exp(logit - lse)``
(xentropy_kernel.cu:428-432: grad = softmax - ((1-s)·onehot + s/C)).  The
same residual contract here via ``jax.custom_vjp``.

Loss semantics (xentropy_kernel.cu:404-410): with smoothing s and C classes,
``loss_i = lse_i - (1-s)·logit_i[y_i] - s·mean_j(logit_ij)`` — i.e. cross
entropy against ``q = (1-s)·onehot + s/C``.  Per-sample losses are returned
(no reduction); rows with ``label == padding_idx`` contribute zero loss and
zero gradient (softmax_xentropy.py:10,24).  One extension over the
reference: columns masked to <= -1e29 (the -1e30 masked-vocab convention —
lane-padded heads, nucleus filtering) are excluded from the smoothing term
and its divisor, so smoothing over a padded head equals the unpadded
model exactly; unmasked inputs are bit-identical to the reference
semantics.  Out-of-range labels are garbage-in: a label >= C reads the
clamped last column under jit (jax gather semantics), a negative label
other than padding_idx clamps to column 0 — neither can raise under
trace; use padding_idx for intentional ignore rows.

Memory discipline (the part the CUDA kernel gets from streaming row-blocks
through shared memory): two measures keep peak HBM bounded at LM shapes,
where a (B·S, 50257) f32 temporary is gigabytes —

* the backward never materializes the one-hot/q tensor: the smoothing term
  folds into the elementwise ``probs - s/C`` and the label column is fixed
  up with a fused iota-compare (never a scatter — see _bwd_row);
* above ``_AUTO_ELEMS`` elements (or always, when ``APEX_TPU_XENT_BLOCK_ROWS``
  is set) both passes run row-blocked under ``lax.map(batch_size=...)`` so
  only one block of f32 temporaries is live at a time.  The GPT seq-1024
  loss shape (16384, 50257) — the on-chip OOM-crash signature this guards
  against (diagnose_gpt1024.jsonl round 4) — chunks into two blocks; the
  seq-128 headline shape stays on the single-shot path.
"""
from __future__ import annotations

import math
import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ...kernels.dispatch import MASKED_LOGIT_THR as _MASK_THR
from ...kernels.dispatch import pallas_mode as _pallas_mode

_f32 = jnp.float32
# Single-shot threshold, in logits elements: one f32 temporary of this size
# is ~2.1 GB.  (16GB v5e; the backward keeps ~2 block-sized f32 temps live.)
_AUTO_ELEMS = 1 << 29


def _use_kernel(mode):
    """Compiled-mode dispatch for the Pallas xentropy kernel: OFF by
    default.  The round-4 on-chip A/B measured the kernel LOSING to
    XLA's own fusion of the jnp expression at both LM loss shapes
    (0.38x at (8192, 50257), 0.74x at (16384, 50257) fwd+bwd — the
    online-softmax block sweep is VPU-bound while XLA's reduce kernels
    are tuned; BENCH_HISTORY round 4), and the GPT seq-128 headline ran
    8% slower with it engaged.  The kernel stays for parity coverage
    (interpret mode always exercises it — that mode exists to test
    kernels) and as the starting point for a future fused
    lm-head+loss kernel; APEX_TPU_XENT_KERNEL=1 forces it on-chip."""
    if mode == "interpret":
        return True
    return mode == "compiled" and \
        os.environ.get("APEX_TPU_XENT_KERNEL", "0") == "1"


def _block_rows(n, c):
    """Rows per chunk; 0 from the env means auto (single-shot when small).

    Auto blocks are BALANCED (ceil(n / n_chunks)) rather than maximal:
    when the chunk count divides ``n`` — every power-of-two LM shape —
    lax.map gets no remainder chunk, which halves the number of large
    programs XLA compiles (the remainder is a second full fwd+bwd body;
    measured ~4-minute seq-1024 compiles with it)."""
    forced = int(os.environ.get("APEX_TPU_XENT_BLOCK_ROWS", "0"))
    if forced > 0:
        return min(forced, n)
    if n * c <= _AUTO_ELEMS:
        return n
    cap = max(1, min(n, _AUTO_ELEMS // max(c, 1)))
    return math.ceil(n / math.ceil(n / cap))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def softmax_cross_entropy_loss(logits, labels, smoothing=0.0, padding_idx=0,
                               half_to_float=False):
    losses, _ = _fwd_math(logits, labels, smoothing, padding_idx)
    if not half_to_float:
        losses = losses.astype(logits.dtype)
    return losses


def _fwd_row(lf_row, label, smoothing, padding_idx):
    lf = lf_row.astype(_f32)
    m = jnp.max(lf)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m)))
    if smoothing:
        # mask-aware smoothing: columns at the -1e30 mask convention
        # (pad_vocab_multiple heads, nucleus_filter) are excluded from
        # the smoothing mean and the divisor is the VALID column count —
        # so a lane-padded head under smoothing>0 produces exactly the
        # unpadded model's loss instead of ~1e25 garbage (a raw
        # mean(lf) would average the ~-1e30 masked log-probs in).
        # Unmasked inputs never reach -1e29, so plain models are
        # untouched; smoothing==0 (static) skips all of this.
        valid = lf > _MASK_THR
        nv = jnp.maximum(jnp.sum(valid.astype(_f32)), 1.0)
        smooth_mean = jnp.sum(jnp.where(valid, lf, 0.0)) / nv
    else:
        smooth_mean = 0.0
    loss = lse - (1.0 - smoothing) * lf[label] - smoothing * smooth_mean
    return jnp.where(label == padding_idx, 0.0, loss), lse


def _rowwise(row_fn, xs, n, block_rows):
    """Apply a per-row function over stacked rows: plain vmap when a single
    block covers everything (identical HLO to hand-batched code — no scan
    wrapper on the hot path), lax.map row-blocks otherwise."""
    if block_rows >= n:
        return jax.vmap(row_fn)(xs)
    return lax.map(row_fn, xs, batch_size=block_rows)


def _fwd_math(logits, labels, smoothing, padding_idx):
    c = logits.shape[-1]
    lead = logits.shape[:-1]
    n = math.prod(lead)
    mode = _pallas_mode()
    if _use_kernel(mode):
        from ...kernels.xentropy import xent_forward
        losses, lse = xent_forward(
            logits.reshape(n, c), labels.reshape(n), smoothing,
            padding_idx, interpret=(mode == "interpret"))
        return losses.reshape(lead), lse.reshape(lead)
    losses, lse = _rowwise(
        lambda xs: _fwd_row(xs[0], xs[1], smoothing, padding_idx),
        (logits.reshape(n, c), labels.reshape(n)),
        n, _block_rows(n, c))
    return losses.reshape(lead), lse.reshape(lead)


def _fwd(logits, labels, smoothing, padding_idx, half_to_float):
    losses, lse = _fwd_math(logits, labels, smoothing, padding_idx)
    out = losses if half_to_float else losses.astype(logits.dtype)
    # residual: logits + one scalar per row — NOT the softmax
    return out, (logits, lse, labels)


def _bwd_row(lf_row, lse, label, g, smoothing, padding_idx, out_dtype):
    c = lf_row.shape[-1]
    probs = jnp.exp(lf_row.astype(_f32) - lse)
    gm = jnp.where(label == padding_idx, 0.0, g.astype(_f32))
    # label-column fixup (q's one-hot part) as an iota-compare, NOT a
    # scatter: the compare fuses into this elementwise chain, while a
    # vmapped scatter-add lowered to an XLA scatter that serialized the
    # whole (rows, vocab) grad — measured 1.6x step-time regression on
    # the seq-128 LM headlines (BENCH_HISTORY round 4).  For a padding
    # label of -1 no column compares equal, and gm is 0 anyway.
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (c,), 0) == label)
    if smoothing:
        # mirror the forward's mask-aware smoothing (see _fwd_row): the
        # s/n_valid term lands only on valid columns, so dlogits on
        # masked columns is exactly 0 (probs there is exp(-1e30-lse)=0)
        lf32 = lf_row.astype(_f32)
        valid = lf32 > _MASK_THR
        nv = jnp.maximum(jnp.sum(valid.astype(_f32)), 1.0)
        smooth_term = jnp.where(valid, smoothing / nv, 0.0)
    else:
        smooth_term = 0.0
    grad = gm * (probs - smooth_term) \
        - ((1.0 - smoothing) * gm) * onehot.astype(_f32)
    return grad.astype(out_dtype)


def _bwd(smoothing, padding_idx, half_to_float, res, g):
    logits, lse, labels = res
    c = logits.shape[-1]
    n = math.prod(logits.shape[:-1])
    mode = _pallas_mode()
    if _use_kernel(mode):
        from ...kernels.xentropy import xent_backward
        lab = labels.reshape(n)
        gm = jnp.where(lab == padding_idx, 0.0,
                       g.reshape(n).astype(_f32))
        grad = xent_backward(logits.reshape(n, c), lab, lse.reshape(n),
                             gm, smoothing,
                             interpret=(mode == "interpret"))
        return grad.reshape(logits.shape), None
    grad = _rowwise(
        lambda xs: _bwd_row(xs[0], xs[1], xs[2], xs[3], smoothing,
                            padding_idx, logits.dtype),
        (logits.reshape(n, c), lse.reshape(n), labels.reshape(n),
         g.reshape(n)),
        n, _block_rows(n, c))
    return grad.reshape(logits.shape), None


softmax_cross_entropy_loss.defvjp(_fwd, _bwd)


class SoftmaxCrossEntropyLoss:
    """Reference-parity callable surface: the reference exposes a
    ``torch.autograd.Function`` used as ``SoftmaxCrossEntropyLoss.apply(...)``
    (softmax_xentropy.py:4)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          padding_idx, half_to_float)
