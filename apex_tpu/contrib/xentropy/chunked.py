"""Chunked LM-head + cross-entropy: the program-level vocab-chain
attack.

The round-4 GPT profile (BENCH_HISTORY, docs/performance.md) attributes
~34 ms of the 69.5 ms seq-128 step to the vocab chain — tied-head
matmul, f32 casts of the (N, V) logits, loss, and backward — while the
same chain costs 15.9 ms in isolation; two Pallas kernel attacks on the
chain measurably lost (0.43x standalone loss, 0.69x fused lm-head+loss)
because XLA's matmuls are already near roofline.  The remaining slack
is how the chain *composes* into the step: full-size (N, V) bf16
logits, two full-size f32 cast passes, and a full-size backward all
live at once.

This module attacks composition instead of kernels: the head matmul and
the loss run over ROW CHUNKS of the flattened (N, E) hidden states
under ``jax.checkpoint``, so

* the live vocab-chain temporaries are one (chunk, V) block instead of
  (N, V) — casts and loss reductions happen block-locally where XLA
  fuses them into the matmul epilogue;
* the backward recomputes each chunk's logits flash-style (the same
  +1 recompute matmul the fused kernel paid) but keeps XLA's own MXU
  scheduling for all three matmuls;
* the head-weight gradient accumulates across chunks through the scan
  transpose in f32.

The models' ``output_hidden=True`` option pairs with this: forward
returns ``(hidden, head_table)`` and the loss owns the chain.

Measured on v5e (BENCH_HISTORY round 5): see the ``--loss-mode`` A/B
rows; this path ships as an option, with the winner of the in-step A/B
promoted to the bench default.
"""
from __future__ import annotations

import math
import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ...kernels.dispatch import MASKED_FILL
from .softmax_xentropy import softmax_cross_entropy_loss


def _chunk_rows(n, v, requested):
    """Rows per chunk.  Default: balanced chunks capped at 1024 rows
    (and ~64M logits elements for very wide heads) — the v5e-measured
    optimum for both LM vocabs (BENCH_HISTORY round 5: GPT 50257 swept
    127..4064 rows, peak at 1016; Llama 32000 likewise) — big enough to
    keep the (chunk, V) @ (V, E) matmuls MXU-shaped, small enough that
    casts/loss fuse block-locally.  Balanced like
    softmax_xentropy._block_rows so power-of-two row counts get no
    remainder chunk."""
    forced = requested or int(os.environ.get("APEX_TPU_LM_CHUNK_ROWS", "0"))
    if forced > 0:
        return min(forced, n)
    cap = max(1, min(n, 1024, (1 << 26) // max(v, 1)))
    if cap >= n:
        return n
    return math.ceil(n / math.ceil(n / cap))


def chunked_lm_head_loss(hidden, head_weight, labels, smoothing=0.0,
                         padding_idx=-100, logical_vocab=None,
                         chunk_rows=None):
    """Per-row cross-entropy of ``hidden @ head_weight.T`` computed and
    differentiated chunkwise — the (N, V) logits never materialize
    whole.

    hidden: (..., E) activations (any leading shape; flattened to rows).
    head_weight: (V, E) — the tied embedding table or an untied
        ``lm_head.weight`` (both store vocab-major).
    labels: integer targets, shape == hidden.shape[:-1]; rows whose
        label equals ``padding_idx`` contribute zero loss and gradient.
    logical_vocab: with a lane-padded head (GptModel
        ``pad_vocab_multiple``), the logical vocab size; pad columns are
        masked to MASKED_FILL before the loss exactly as the model's
        ``_mask_pad_logits`` would, and mask-aware smoothing keeps
        smoothed losses exact.
    chunk_rows: rows per chunk (default: auto ~64M logits elements;
        APEX_TPU_LM_CHUNK_ROWS overrides).

    Returns per-row losses with hidden's leading shape, f32.
    """
    e = hidden.shape[-1]
    lead = hidden.shape[:-1]
    if labels.shape != lead:
        raise ValueError(
            f"chunked_lm_head_loss: labels shape {labels.shape} must "
            f"equal hidden's leading shape {lead}")
    v = head_weight.shape[0]
    n = math.prod(lead)
    x2d = hidden.reshape(n, e)
    lab = labels.reshape(n).astype(jnp.int32)
    chunk = _chunk_rows(n, v, chunk_rows)

    def body(args):
        xc, lc = args                                   # (chunk, E), (chunk,)
        logits = jnp.matmul(xc, head_weight.T.astype(xc.dtype))
        if logical_vocab is not None and logical_vocab < v:
            cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
            logits = jnp.where(cols < logical_vocab, logits,
                               jnp.asarray(MASKED_FILL, logits.dtype))
        return softmax_cross_entropy_loss(logits, lc, smoothing,
                                          padding_idx, True)

    if chunk >= n:
        losses = body((x2d, lab))
    else:
        k = math.ceil(n / chunk)
        n_p = k * chunk
        if n_p != n:
            # pad rows are sliced off below; the slice transpose feeds
            # them zero cotangents, so they contribute no gradient
            x2d = jnp.pad(x2d, ((0, n_p - n), (0, 0)))
            lab = jnp.pad(lab, (0, n_p - n),
                          constant_values=padding_idx)
        # checkpoint: the (chunk, V) logits are recomputed in the
        # backward instead of saved — the scan carries no vocab-sized
        # residuals, and head_weight's cotangent accumulates across
        # chunks through the scan transpose
        losses = lax.map(jax.checkpoint(body),
                         (x2d.reshape(k, chunk, e),
                          lab.reshape(k, chunk)))
        losses = losses.reshape(n_p)[:n]
    return losses.reshape(lead)


def make_chunked_lm_loss(vocab_size=None, smoothing=0.0, padding_idx=-100,
                         shift=True, chunk_rows=None):
    """Loss-fn factory for ``make_train_step`` over an
    ``output_hidden=True`` LM: ``loss_fn((hidden, table), ids)`` computes
    the next-token (``shift=True``) or aligned chunked head loss, mean
    over rows.  ``vocab_size``: the LOGICAL vocab for lane-padded heads
    (None: the table's full height)."""
    def loss_fn(out, ids):
        hidden, table = out
        if shift:
            hidden = hidden[:, :-1]
            ids = ids[:, 1:]
        per = chunked_lm_head_loss(
            hidden, table, ids, smoothing=smoothing,
            padding_idx=padding_idx, logical_vocab=vocab_size,
            chunk_rows=chunk_rows)
        return jnp.mean(per)
    return loss_fn
