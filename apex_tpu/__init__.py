"""apex_tpu — a TPU-native re-design of NVIDIA Apex (reference: /root/reference).

A standalone JAX/XLA/Pallas framework providing Apex's user-facing surface —
``amp.initialize`` O0–O3, ``amp.scale_loss``, ``parallel.DistributedDataParallel``,
``SyncBatchNorm``, the ``Fused*`` optimizers, ``FusedLayerNorm``, ``MLP`` and the
``multi_tensor_*`` suite — built TPU-first: pure jitted step functions, dtype
policies applied at trace time, collectives as mesh ops over ICI, and Pallas
kernels where fusion matters.

Mirrors apex/__init__.py:1-20 eager subpackage imports.
"""

from . import compat  # noqa: F401  (jax-version shims; polyfills jax.shard_map)
from . import ops  # noqa: F401  (kernel substrate; the "amp_C" equivalent)
from . import multi_tensor_apply  # noqa: F401

__version__ = "0.1.0"

# Eager subpackage imports, mirroring the reference's `import apex` surface.
from . import amp  # noqa: F401,E402
from . import optimizers  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import normalization  # noqa: F401,E402
from . import parallel  # noqa: F401,E402
from . import fp16_utils  # noqa: F401,E402
from . import mlp  # noqa: F401,E402
from . import pyprof  # noqa: F401,E402
