"""Stall watchdog: a heartbeat thread that notices when training stops.

BENCH_r05 wedged for a full budget window at backend init with nothing
watching the dispatch loop — the failure mode this module exists for.
The train loop (and anything else that makes forward progress) calls
``heartbeat()``; ``StallWatchdog`` polls and, when no heartbeat lands
within ``deadline_s``, emits one typed ``watchdog.stall`` diagnostic
carrying the last open span, the last completed step, the backend
state, and the stale-tunnel remediation hint — then stays quiet until
progress resumes (one diagnostic per distinct stall, not one per poll).

``heartbeat()`` is a lock + two float stores: cheap enough to call
every step. It is host-side instrumentation (OBS-IN-JIT applies).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from . import registry as _registry
from . import spans as _spans

STALL_HINT = (
    "stale axon tunnel claim: a dead client is likely still holding the "
    "single-claim TPU tunnel — restart the tunnel (probe_tunnel.sh) or "
    "wait for its lease to lapse, then rerun; if the backend is healthy, "
    "check the last span below for the phase that stopped making progress"
)

_hb_lock = threading.Lock()
_last_beat: Optional[float] = None
_last_step: Optional[int] = None


def heartbeat(step: Optional[int] = None) -> None:
    """Record forward progress; called by TrainStep after each window."""
    global _last_beat, _last_step
    with _hb_lock:
        _last_beat = time.monotonic()
        if step is not None:
            _last_step = step


def last_heartbeat():
    with _hb_lock:
        return _last_beat, _last_step


class StallWatchdog:
    """Daemon thread firing a diagnostic when heartbeats stop.

    >>> wd = StallWatchdog(deadline_s=30.0)
    >>> wd.start()
    ... # train; TrainStep.__call__ heartbeats automatically
    >>> wd.stop()
    """

    def __init__(self, deadline_s: float, poll_s: Optional[float] = None,
                 on_stall: Optional[Callable[[Dict[str, Any]], None]] = None):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.poll_s = float(poll_s) if poll_s is not None \
            else self.deadline_s / 4.0
        self.on_stall = on_stall
        self.stalls: list = []       # diagnostics, for tests / callers
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._start_time: Optional[float] = None
        self._fired_for: Optional[float] = None   # beat we already flagged

    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        self._start_time = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="apex-tpu-stall-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s * 4 + 1.0)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            beat, step = last_heartbeat()
            anchor = beat if beat is not None else self._start_time
            silence = time.monotonic() - anchor
            if silence < self.deadline_s:
                continue
            if self._fired_for == anchor:
                continue             # already diagnosed this stall
            self._fired_for = anchor
            self._fire(silence, step)

    def _fire(self, silence_s: float, step: Optional[int]) -> None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception as e:       # backend wedged/uninitialized
            backend = f"unavailable: {type(e).__name__}"
        diag = {
            "deadline_s": self.deadline_s,
            "since_last_step_s": silence_s,
            "last_step": step,
            "last_span": _spans.last_span(),
            "backend": backend,
            "hint": STALL_HINT,
        }
        self.stalls.append(diag)
        _registry.event("watchdog.stall", **diag)
        if self.on_stall is not None:
            try:
                self.on_stall(diag)
            except Exception:
                pass                 # a bad callback must not kill the thread
