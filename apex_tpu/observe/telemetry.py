"""Zero-dispatch on-device step telemetry.

The fused train step (PR 3) already carries a donated ``StepState``
through an on-device microbatch scan with skip-flag discipline — the
whole point is 1 compile + 1 dispatch per K-microbatch window and no
host syncs inside the window. Telemetry must not break that, so the
observable quantities (per-window loss, global grad-norm, loss scale,
overflow count) are *accumulated into the same donated carry* with pure
``jnp`` arithmetic and drained to host only every ``drain_every``
windows, from eager code outside jit (``TrainStep.drain_telemetry``).

Everything in this module is jit-safe by construction — it is the one
piece of `apex_tpu.observe` that is *meant* to run inside traced code,
which is why the OBS-IN-JIT lint rule deliberately does not flag
``accumulate``/``init_telemetry``.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class StepTelemetry(NamedTuple):
    """On-device accumulator riding in ``StepState.telem``.

    - ``loss_sum``: sum of per-window mean losses since the last drain
      (host divides by ``windows`` for the mean).
    - ``grad_norm``: global L2 norm of the *last* window's master grads
      (a sum across drains would be meaningless; last-value is what a
      dashboard wants).
    - ``loss_scale``: loss scale after the last window's update.
    - ``overflow_count``: number of overflow-skipped windows since the
      last drain.
    - ``windows``: windows accumulated since the last drain.
    """
    loss_sum: jnp.ndarray
    grad_norm: jnp.ndarray
    loss_scale: jnp.ndarray
    overflow_count: jnp.ndarray
    windows: jnp.ndarray


def init_telemetry() -> StepTelemetry:
    f32 = jnp.float32
    return StepTelemetry(
        loss_sum=jnp.zeros((), f32),
        grad_norm=jnp.zeros((), f32),
        loss_scale=jnp.ones((), f32),
        overflow_count=jnp.zeros((), jnp.int32),
        windows=jnp.zeros((), jnp.int32),
    )


def accumulate(telem: StepTelemetry, *, loss, master_grads, flag,
               loss_scale, mean_axes=()) -> StepTelemetry:
    """Fold one window's observables into the carry (traced code).

    ``master_grads`` are the f32 (unscaled) gradients the optimizer
    consumed; ``flag`` is the window's overflow flag (True = skipped).
    The grad norm is computed in f32 over the master grads, so at
    ``loss_scale == 1.0`` it is bitwise-identical to an eager
    ``sqrt(sum(g*g))`` over the same gradients.

    ``mean_axes``: mapped mesh axis names to pmean the loss over —
    the cross-mesh reduction for steps running under ``shard_map``
    (dp / sp axes; the fused step threads them).  Only the loss needs
    it: by the time ``accumulate`` runs, the gradients have been
    through the DP psum-average / TP block psum, so every device holds
    the same replicated values and the grad norm — like the overflow
    flag and the loss scale — is already mesh-wide.  Under GSPMD
    (ZeRO) the step is a single global-view program and the loss is
    global already; pass no axes there.
    """
    gsq = jnp.zeros((), jnp.float32)
    for g in master_grads:
        gsq = gsq + jnp.sum(g * g)
    gnorm = jnp.sqrt(gsq)
    loss = jnp.asarray(loss, jnp.float32) if loss is not None \
        else jnp.zeros((), jnp.float32)
    for ax in tuple(mean_axes):
        loss = jax.lax.pmean(loss, ax)
    return StepTelemetry(
        loss_sum=telem.loss_sum + loss,
        grad_norm=gnorm,
        loss_scale=jnp.asarray(loss_scale, jnp.float32),
        overflow_count=telem.overflow_count + flag.astype(jnp.int32),
        windows=telem.windows + 1,
    )
