"""Metrics registry + structured event log.

One process-global choke point for every number the runtime wants to
report: `step_cache.stats()` counters, checkpoint save/restore
latencies, chaos injections, elastic replan/reshard timings, planner
decisions, and the fused step's drained on-device telemetry all land
here instead of in per-subsystem private dicts.

Design constraints:

- **Thread-safe.** The prefetch worker, the async-checkpoint writer,
  and the stall watchdog all emit from their own threads.
- **Host-side only.** Nothing in this module may be called from
  jit-traced code (enforced by the OBS-IN-JIT lint rule) — every entry
  point touches a lock and Python containers, which inside a traced
  function would be a silent host round-trip at best.
- **Cheap.** A counter bump is a dict lookup + integer add under an
  RLock; no I/O unless a JSONL sink is attached.
- **Monotonic timestamps.** Event records carry `ts_ms` from
  `time.monotonic()` so ordering survives wall-clock steps (NTP slew
  on long runs); sinks that need wall time can add their own.
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

_EVENT_BUFFER_MAX = 4096


class Counter:
    """Monotonically increasing integer metric."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> int:
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar metric."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        return self._value


class Histogram:
    """Streaming summary: count / total / min / max / last.

    Full per-sample retention belongs in the event log (attach a JSONL
    sink); the in-memory histogram keeps O(1) state so hot paths like
    per-step latencies never grow memory.
    """

    __slots__ = ("name", "count", "total", "min", "max", "last", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.last: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            self.last = value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"count": self.count, "total": self.total,
                    "mean": self.mean, "min": self.min, "max": self.max,
                    "last": self.last}


class MetricsRegistry:
    """Named metrics plus a bounded structured event log.

    Events are dicts `{"schema": 1, "ts_ms": <monotonic ms>,
    "event": <name>, ...fields}`; the newest `_EVENT_BUFFER_MAX` are
    kept in memory and every event is appended to any attached JSONL
    sinks as one line.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._events: collections.deque = collections.deque(
            maxlen=_EVENT_BUFFER_MAX)
        self._sinks: Dict[str, Any] = {}   # path -> open file handle

    # -- metric accessors (create-on-first-use) ---------------------------

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    # -- events ------------------------------------------------------------

    def event(self, name: str, **fields: Any) -> Dict[str, Any]:
        rec = {"schema": SCHEMA_VERSION,
               "ts_ms": time.monotonic() * 1e3,
               "event": name}
        rec.update(fields)
        with self._lock:
            self._events.append(rec)
            sinks = list(self._sinks.values())
        for fh in sinks:
            try:
                fh.write(json.dumps(rec, default=str) + "\n")
                fh.flush()
            except (OSError, ValueError):
                pass   # a dead sink must never take down the train loop
        return rec

    def events(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self._events)
        if name is None:
            return evs
        return [e for e in evs if e["event"] == name]

    def add_jsonl_sink(self, path: str) -> None:
        with self._lock:
            if path not in self._sinks:
                self._sinks[path] = open(path, "a")

    def remove_jsonl_sink(self, path: str) -> None:
        with self._lock:
            fh = self._sinks.pop(path, None)
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    # -- introspection / reset ---------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every metric (events excluded — use
        ``events()``)."""
        with self._lock:
            return {
                "schema": SCHEMA_VERSION,
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {n: h.summary()
                               for n, h in self._histograms.items()},
            }

    def remove(self, prefix: str) -> None:
        """Drop every metric whose name starts with ``prefix``.

        Lets a subsystem reset its slice (``step_cache.reset_stats()``)
        without clobbering unrelated metrics.
        """
        with self._lock:
            for table in (self._counters, self._gauges, self._histograms):
                for name in [n for n in table if n.startswith(prefix)]:
                    del table[name]

    def clear_events(self) -> None:
        with self._lock:
            self._events.clear()

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._events.clear()


# -- process-global default registry ---------------------------------------

_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default


def counter(name: str) -> Counter:
    return _default.counter(name)


def gauge(name: str) -> Gauge:
    return _default.gauge(name)


def histogram(name: str) -> Histogram:
    return _default.histogram(name)


def event(name: str, **fields: Any) -> Dict[str, Any]:
    return _default.event(name, **fields)


def events(name: Optional[str] = None) -> List[Dict[str, Any]]:
    return _default.events(name)
