"""Metric catalog: the documented name → meaning table.

The registry (``registry.py``) is deliberately schema-free — any string
names a counter.  That is right for the emit side and wrong for the
consume side: dashboards, the bench stages, and tests need one place
that says what a name MEANS, its instrument kind, and its unit.  The
catalog is that place, starting with the rollout subsystem (whose
metrics are new in this PR and consumed by ``bench --rollout``); other
subsystems can grow entries without touching the registry.

``tests/test_rollout.py`` pins the contract from both sides: every
``rollout.*`` name the runtime emits is cataloged, and the catalog
names only instruments of the kind actually registered.
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["CATALOG", "describe", "names"]

#: name -> {kind, unit, description}.  ``kind`` is one of
#: "counter" | "gauge" | "histogram" | "event".
CATALOG: Dict[str, Dict[str, str]] = {
    # -- rollout: weight publish ------------------------------------------
    "rollout.weight_sync": {
        "kind": "event", "unit": "record",
        "description": "One train→serve weight publish: which weight "
                       "set, new epoch, sync wall-ms, zero-copy vs "
                       "copied leaf counts, bytes moved."},
    "rollout.weight_sync_ms": {
        "kind": "histogram", "unit": "ms",
        "description": "Wall time of one weight publish (cast dispatch "
                       "+ reshard + hot-swap)."},
    "rollout.zero_copy_frac": {
        "kind": "gauge", "unit": "fraction",
        "description": "Fraction of leaves in the last publish that "
                       "rode the layout-identical zero-copy fast path."},
    "rollout.publishes": {
        "kind": "counter", "unit": "publishes",
        "description": "Weight publishes since process start (target "
                       "and draft)."},
    # -- rollout: buffer ---------------------------------------------------
    "rollout.samples": {
        "kind": "counter", "unit": "samples",
        "description": "Finished rollouts accepted into the buffer."},
    "rollout.buffer.rejects": {
        "kind": "counter", "unit": "samples",
        "description": "Pushes refused by a full buffer (unreachable "
                       "under the runtime's slot reservation — nonzero "
                       "means a caller skipped backpressure)."},
    "rollout.buffer_fill": {
        "kind": "gauge", "unit": "samples",
        "description": "Live samples in the buffer."},
    "rollout.evicted_stale": {
        "kind": "counter", "unit": "samples",
        "description": "Samples dropped for exceeding the staleness "
                       "bound (drop policy)."},
    "rollout.staleness": {
        "kind": "histogram", "unit": "weight-epochs",
        "description": "Sample age (current epoch - admission epoch) "
                       "at every training draw."},
    "rollout.backpressure": {
        "kind": "counter", "unit": "rounds",
        "description": "Rounds where generation was throttled because "
                       "the buffer lacked free slots (trainer behind)."},
    # -- rollout: loop -----------------------------------------------------
    "rollout.round": {
        "kind": "event", "unit": "record",
        "description": "One generate→train round: submissions, "
                       "evictions, last loss, windowed accept rate, "
                       "epoch, buffer fill, staleness p50."},
    "rollout.train_steps": {
        "kind": "counter", "unit": "steps",
        "description": "Fused train steps consumed from the buffer."},
    "rollout.weight_epoch": {
        "kind": "gauge", "unit": "epoch",
        "description": "Target weight epoch currently being served."},
    "rollout.restore": {
        "kind": "event", "unit": "record",
        "description": "A rollout job resumed from checkpoint: round, "
                       "epoch, buffer fill."},
    # -- rollout: online distillation -------------------------------------
    "rollout.distill_steps": {
        "kind": "counter", "unit": "steps",
        "description": "Draft distillation steps taken."},
    "rollout.distill_publish": {
        "kind": "event", "unit": "record",
        "description": "A draft publish: new draft epoch, acceptance "
                       "rate observed under the OUTGOING draft, last "
                       "distill loss."},
    # -- serve: the hot-swap seam the rollout loop drives ------------------
    "serve.weight_swap": {
        "kind": "event", "unit": "record",
        "description": "ServeEngine.publish_weights applied: weight "
                       "set, epoch now served, tick, leaf count."},
    # -- serve: prefix cache (content-addressed KV block reuse) ------------
    "serve.prefix.hit_rate": {
        "kind": "gauge", "unit": "fraction",
        "description": "Prompt tokens admission found already cached "
                       "over all prompt tokens submitted, engine "
                       "lifetime-cumulative (docs/serving.md, Prefix "
                       "caching)."},
    "serve.prefix.tokens_saved": {
        "kind": "counter", "unit": "tokens",
        "description": "Prompt tokens whose prefill was skipped "
                       "because their KV blocks were adopted from the "
                       "hash index."},
    "serve.prefix.cow_forks": {
        "kind": "counter", "unit": "blocks",
        "description": "Copy-on-write forks of shared blocks (a "
                       "full-chain hit re-ingests its final token into "
                       "an exclusive copy)."},
    "serve.cache.evictions": {
        "kind": "counter", "unit": "blocks",
        "description": "Cached-tier blocks evicted under allocation "
                       "pressure (hash entry dropped, id returned to "
                       "the free list)."},
    "serve.pool.free": {
        "kind": "gauge", "unit": "blocks",
        "description": "Free-list blocks: allocatable without evicting "
                       "any cached-tier entry."},
    "serve.pool.cached": {
        "kind": "gauge", "unit": "blocks",
        "description": "Cached-tier blocks: refcount zero with a live "
                       "hash entry — reclaimable headroom, not "
                       "occupancy."},
    "serve.pool.active": {
        "kind": "gauge", "unit": "blocks",
        "description": "Blocks held by at least one live block table "
                       "(refcount >= 1)."},
    # -- planner: the joint pp×remat×offload×ep search ---------------------
    "plan.search_ms": {
        "kind": "gauge", "unit": "ms",
        "description": "Wall time of the last plan_training joint "
                       "search (enumerate → prune → rank, including "
                       "ledger re-pricing)."},
    "plan.explored": {
        "kind": "gauge", "unit": "plans",
        "description": "Plans enumerated by the last joint search, "
                       "feasible and rejected alike — nothing is "
                       "pruned before it is counted."},
    "plan.pruned_oom": {
        "kind": "gauge", "unit": "plans",
        "description": "Plans the last search rejected as "
                       "memory-infeasible under the per-device HBM "
                       "model (reason strings carry the breakdown)."},
    "plan.bubble_frac": {
        "kind": "gauge", "unit": "fraction",
        "description": "Pipeline bubble fraction (pp-1)/(micro+pp-1) "
                       "of the chosen plan; set only when the winner "
                       "pipelines (pp > 1)."},
}


def names(prefix: str = "") -> list:
    """Cataloged metric names, optionally filtered by prefix."""
    return sorted(n for n in CATALOG if n.startswith(prefix))


def describe(name: str) -> Optional[Dict[str, str]]:
    """The catalog entry for ``name``, or None if uncataloged."""
    return CATALOG.get(name)
