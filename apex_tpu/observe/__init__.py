"""apex_tpu.observe — unified trace/metrics runtime.

One telemetry choke point for the whole library:

- :mod:`registry` — thread-safe counters/gauges/histograms + a
  structured JSONL event log (schema-versioned, monotonic timestamps).
- :mod:`spans` — ``span("ckpt.save")`` context manager emitting both
  the event log and ``jax.profiler.TraceAnnotation``.
- :mod:`telemetry` — the jit-safe on-device step accumulator carried in
  ``StepState.telem`` (the one submodule allowed inside traced code).
- :mod:`watchdog` — heartbeat thread firing a typed stall diagnostic.
- :mod:`catalog` — the documented name → meaning table for metric
  consumers (dashboards, bench stages, tests).

Everything except :mod:`telemetry` is host-side only; calls reachable
from jit-traced code are flagged by the OBS-IN-JIT lint rule.
"""
from .catalog import CATALOG, describe
from .catalog import names as catalog_names
from .registry import (SCHEMA_VERSION, Counter, Gauge, Histogram,
                       MetricsRegistry, counter, event, events, gauge,
                       get_registry, histogram)
from .spans import last_span, span
from .telemetry import StepTelemetry, accumulate, init_telemetry
from .watchdog import STALL_HINT, StallWatchdog, heartbeat, last_heartbeat

__all__ = [
    "SCHEMA_VERSION", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "event", "events", "get_registry",
    "span", "last_span",
    "StepTelemetry", "init_telemetry", "accumulate",
    "StallWatchdog", "heartbeat", "last_heartbeat", "STALL_HINT",
    "CATALOG", "describe", "catalog_names",
]
