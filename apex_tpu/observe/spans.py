"""Trace spans: one context manager, two outputs.

``span("ckpt.save")`` emits (a) a structured event + latency histogram
into the metrics registry and (b) a ``jax.profiler.TraceAnnotation`` so
the same region shows up in device profiles — host events and XLA
timelines line up by name.

Spans are host-side instrumentation; entering one from jit-traced code
is a host round-trip and is flagged by the OBS-IN-JIT lint rule.
Thread-safe: the prefetch worker and async-checkpoint writer open spans
on their own threads, and the watchdog reads ``last_span()`` from its
heartbeat thread.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Optional

from . import registry as _registry

_state_lock = threading.Lock()
_last_span: Optional[Dict[str, Any]] = None

_trace_annotation = None
_trace_annotation_probed = False


def _get_trace_annotation():
    """Resolve jax.profiler.TraceAnnotation lazily; spans must work (as
    log-only) even when jax or its profiler is unavailable."""
    global _trace_annotation, _trace_annotation_probed
    if not _trace_annotation_probed:
        _trace_annotation_probed = True
        try:
            from jax import profiler as _profiler
            _trace_annotation = _profiler.TraceAnnotation
        except Exception:
            _trace_annotation = None
    return _trace_annotation


def last_span() -> Optional[Dict[str, Any]]:
    """Most recently *started* span (it may still be open) — the stall
    watchdog reports this as "where the runtime was last seen"."""
    with _state_lock:
        return dict(_last_span) if _last_span else None


@contextlib.contextmanager
def span(name: str, **fields: Any):
    """Time a region; emit a ``span`` event and a ``span.<name>_ms``
    histogram sample on exit, wrapped in a profiler TraceAnnotation."""
    global _last_span
    t0 = time.monotonic()
    with _state_lock:
        _last_span = {"span": name, "started_ms": t0 * 1e3, **fields}
    annotation = _get_trace_annotation()
    cm = annotation(name) if annotation is not None \
        else contextlib.nullcontext()
    try:
        with cm:
            yield
    finally:
        dur_ms = (time.monotonic() - t0) * 1e3
        _registry.histogram(f"span.{name}_ms").observe(dur_ms)
        _registry.event("span", span=name, dur_ms=dur_ms, **fields)
