"""apex_tpu.rollout — the generate-then-train runtime.

Closes the serve/train loop the repo has so far run only in separate
jobs: a :class:`RolloutRuntime` drives a ServeEngine generating
continuations while the fused train step consumes completed rollouts
from a bounded-staleness :class:`RolloutBuffer`, and trainer weights
flow back serve-ward through the measured, versioned
:class:`WeightPublisher` (reshard_state + the layout-identical
zero-copy fast path).  :class:`OnlineDistiller` is the first concrete
scenario: the speculative draft trains continuously against live
acceptance telemetry and publishes improved drafts into the engine's
speculative pool.  See docs/rollout.md.
"""
from .buffer import RolloutBuffer, RolloutSample
from .distill import OnlineDistiller
from .publish import WeightPublisher, master_leaves
from .runtime import RolloutRuntime

__all__ = ["RolloutBuffer", "RolloutSample", "OnlineDistiller",
           "WeightPublisher", "master_leaves", "RolloutRuntime"]
