"""The one measured train→serve weight-movement surface.

A rollout loop republishes trainer weights into a live
:class:`~apex_tpu.serve.engine.ServeEngine` every few steps, and that
movement is exactly the cross-layout resharding the elastic restore
path already owns: the publish path is ``reshard_state`` pointed at the
serve model's current values, so layout-identical leaves ride the
zero-copy fast path and only genuinely relaid-out leaves pay a copy —
priced, never implicit (arXiv:2004.13336's thesis applied to the
train→serve direction).

Three jobs live here:

* :func:`master_leaves` — read a fused train step's fp32 masters in
  ``model.parameters()`` order (flat-master steps un-flatten row by
  row), WITHOUT a host round-trip;
* the ``weight_publish`` cast program — when the serve model runs a
  different dtype, every master is cast ONCE in a single fused executor
  dispatch (kind ``weight_publish``; spans + heartbeats like any other
  forward-progress unit).  Same-dtype publishes skip the dispatch
  entirely;
* :class:`WeightPublisher` — ties cast + reshard + engine hot-swap
  together, stamps a monotonically growing weight epoch, and emits the
  ``rollout.weight_sync`` event with per-leaf zero-copy hit stats
  (``reshard_state(stats_out=...)``) so "how much did this sync cost"
  is a measurement, not a guess.

This module is one of the sanctioned homes of the WEIGHT-PUBLISH lint
rule: raw ``jax.device_put``/``jax.device_get`` of parameter pytrees
anywhere else is a finding — weight movement goes through here or
through resilience's reshard surface.
"""
from __future__ import annotations

import itertools
import time
from typing import List, Optional

import jax.numpy as jnp

from ..observe import registry as _obs
from ..runtime import executor as _executor
from ..runtime.resilience import reshard_state

__all__ = ["WeightPublisher", "master_leaves"]

#: per-publisher token in the cast program's static key — two publishers
#: over identically-shaped models must not share a cache entry (their
#: closures hold different dtype tuples only, but the token keeps the
#: keying rule uniform with the serve engine's)
_PUBLISH_TOKENS = itertools.count()


def master_leaves(step) -> List:
    """A fused train step's fp32 master values, aligned with
    ``model.parameters()`` order.

    Plain steps keep masters as a per-parameter list; flat-master steps
    (``flat_master=True``) keep one fused buffer per dtype bucket, so
    each leaf is sliced back out row by row (the same ``_row`` the
    step's own ``sync_to_objects`` uses).  Either way the result is the
    list :class:`WeightPublisher` publishes — no host round-trip.
    """
    st = step.state
    meta = getattr(step, "_flat_meta", None)
    if meta is None:
        return list(st.master_params)
    from ..training.step import _row
    return [_row(st.master_params[bid], j, meta.shapes[i])
            for i, (bid, j) in enumerate(meta.pos)]


def _make_cast(dtype_names):
    def cast(srcs):
        return [s.astype(dt) for s, dt in zip(srcs, dtype_names)]
    return cast


class WeightPublisher:
    """Publish train masters into a live serve engine, measured and
    versioned.

    One publisher per (engine, weight set): ``which="target"`` swaps the
    served model, ``which="draft"`` the speculative draft.  Each
    :meth:`publish` is cast-once (a single ``weight_publish`` executor
    dispatch, skipped when every dtype already matches), resharded under
    the serve values' current layout (zero-copy where identical), and
    hot-swapped between ticks via ``engine.publish_weights`` — no serve
    program recompiles (config-only static keys).  The new weight epoch
    is returned in the stats dict and every subsequent admission is
    attributed to it.
    """

    def __init__(self, engine, *, which: str = "target"):
        if which == "draft" and not engine.spec:
            raise ValueError("WeightPublisher(which='draft') needs a "
                             "speculative engine (draft=...)")
        self.engine = engine
        self.which = which
        self._token = next(_PUBLISH_TOKENS)
        model = engine.draft if which == "draft" else engine.model
        self._tgt_params = list(model.parameters())
        self.publishes = 0
        self.last_stats: dict = {}

    @property
    def epoch(self) -> int:
        """The weight epoch currently being served for this weight set."""
        return self.engine.weight_epochs[self.which]

    def publish(self, masters, *, epoch: Optional[int] = None) -> dict:
        """Cast once → reshard → hot-swap.  Returns the stats dict
        (also kept as ``last_stats`` and emitted as a
        ``rollout.weight_sync`` event): epoch, ``weight_sync_ms``,
        zero-copy hit/miss leaf counts, bytes moved, and whether the
        cast dispatch ran."""
        t0 = time.perf_counter()
        masters = list(masters)
        tgt_vals = [p.data for p in self._tgt_params]
        if len(masters) != len(tgt_vals):
            raise ValueError(
                f"publish({self.which!r}): {len(masters)} master leaves "
                f"for {len(tgt_vals)} serve parameters — different "
                f"model config")
        dtype_names = tuple(jnp.dtype(v.dtype).name for v in tgt_vals)
        src_names = tuple(jnp.dtype(m.dtype).name for m in masters)
        # under buffer donation (tpu/gpu) the zero-copy pass-through
        # would alias serve weights to master buffers the NEXT train
        # step's donation invalidates — force the fused dispatch so the
        # published leaves own their storage; on cpu (donation off)
        # aliasing is safe and same-dtype publishes stay zero-cost
        cast = (src_names != dtype_names
                or _executor.donation.enabled)
        if cast:
            prog = _executor.Program(
                "weight_publish",
                ("weight_publish", self._token, dtype_names),
                _make_cast(dtype_names))
            masters = _executor.executor.submit(
                prog, (masters,), step=self.publishes + 1)
        rs: dict = {}
        placed = reshard_state(
            masters, tgt_vals, component=f"publish/{self.which}",
            source="<train-step>", stats_out=rs)
        ep = self.engine.publish_weights(placed, which=self.which,
                                         epoch=epoch)
        self.publishes += 1
        ms = (time.perf_counter() - t0) * 1e3
        leaves = rs.get("leaves", 0)
        frac = (rs.get("zero_copy", 0) / leaves) if leaves else 1.0
        stats = {"which": self.which, "epoch": ep,
                 "weight_sync_ms": ms, "cast_dispatch": cast,
                 "leaves": leaves, "zero_copy": rs.get("zero_copy", 0),
                 "copied": rs.get("copied", 0),
                 "bytes_moved": rs.get("bytes_moved", 0),
                 "zero_copy_frac": frac}
        _obs.event("rollout.weight_sync", **stats)
        _obs.histogram("rollout.weight_sync_ms").observe(ms)
        _obs.gauge("rollout.zero_copy_frac").set(frac)
        _obs.counter("rollout.publishes").inc()
        stats["per_leaf"] = rs.get("per_leaf", [])
        self.last_stats = stats
        return stats

    def restore(self, leaves, *, epoch: int) -> dict:
        """Republish checkpointed serve weights at their SAVED epoch —
        the resume half of a rollout checkpoint.  ``leaves`` were saved
        in the serve dtype already, so no cast runs; ``reshard_state``
        re-devices host arrays under the current layout bit-exact."""
        return self.publish(leaves, epoch=epoch)
