"""Online draft distillation — the rollout loop's first concrete
scenario.

A speculative serve engine is only as fast as its draft's acceptance
rate, and acceptance decays as the TARGET trains away from whatever the
draft was distilled on.  The fix is to close the loop: keep a trainable
draft master distilling continuously against the live target (hard
labels — the exact event the acceptance rule tests, see
``inference/draft.py``), watch the engine's own
``serve.spec.accept_rate`` telemetry, and publish improved drafts back
into the engine's speculative pool through the same measured
weight-publish path the target uses.

:class:`OnlineDistiller` owns the three pieces: the persistent
:func:`~apex_tpu.inference.draft.make_distill_step` (optimizer moments
and the compiled program survive across publish windows), a
``which="draft"`` :class:`~apex_tpu.rollout.publish.WeightPublisher`,
and the publish log pairing each draft epoch with the acceptance rate
observed in the window before it — the trend line ``bench --rollout``
reports as ``accept_rate_trend``.

Labels read the ENGINE's target model at call time, so after every
target publish the distillation objective tracks the weights actually
being served — distill toward what speculation will be verified
against, not toward a stale training-side copy.
"""
from __future__ import annotations

from typing import List, Optional

from ..inference.draft import make_distill_step
from ..observe import registry as _obs
from .publish import WeightPublisher, master_leaves

__all__ = ["OnlineDistiller"]


class OnlineDistiller:
    def __init__(self, engine, draft_master, *, lr: float = 1e-3):
        if not engine.spec:
            raise ValueError("OnlineDistiller needs a speculative engine "
                             "(ServeEngine(draft=...))")
        self.engine = engine
        self.draft_master = draft_master
        self.dstep = make_distill_step(draft_master, engine.model, lr=lr)
        self.publisher = WeightPublisher(engine, which="draft")
        self.losses: List[float] = []
        self.publish_log: List[dict] = []

    def train_on(self, xs) -> float:
        """One fused distillation step on a ``(B,S)`` id batch (rollout
        windows drawn from the buffer — the draft distills on the same
        distribution it will be asked to speculate on)."""
        loss = self.dstep(xs)
        self.losses.append(loss)
        _obs.counter("rollout.distill_steps").inc()
        return loss

    def publish(self, *, accept_rate: Optional[float] = None) -> dict:
        """Publish the draft master into the engine's speculative pool
        (cast-once through the measured path) and log the acceptance
        rate observed under the OUTGOING draft — the before/after pairs
        are the improvement evidence."""
        stats = self.publisher.publish(master_leaves(self.dstep.step))
        rec = {"epoch": stats["epoch"], "accept_rate": accept_rate,
               "loss_last": self.losses[-1] if self.losses else None}
        self.publish_log.append(rec)
        _obs.event("rollout.distill_publish", **rec)
        return stats
