"""RolloutRuntime: the generate-then-train loop.

One runtime owns one :class:`~apex_tpu.serve.engine.ServeEngine` (the
generator), one fused train step (the consumer), one
:class:`~apex_tpu.rollout.buffer.RolloutBuffer` between them, and the
measured weight-publish path closing the loop.  Work proceeds in
deterministic *rounds*:

1. **evict** — samples older than the staleness bound leave the buffer;
2. **generate** — up to ``rollouts_per_round`` seeded prompts are
   submitted, THROTTLED to the buffer's free slots (backpressure: when
   the trainer falls behind, the serve side generates less, never
   drops a finished rollout);
3. **harvest** — finished continuations enter the buffer stamped with
   the weight epoch they were admitted under;
4. **train** — ``train_steps_per_round`` fused steps on seeded windows
   drawn from the buffer (and, when an
   :class:`~apex_tpu.rollout.distill.OnlineDistiller` is attached,
   ``distill_steps_per_round`` draft-distillation steps on the same
   distribution);
5. **publish** — every ``publish_every`` rounds the trainer's masters
   flow serve-ward (cast once, resharded zero-copy where layouts
   match, epoch bumped); draft publishes ride their own cadence with
   the acceptance rate observed under the outgoing draft logged next
   to the new epoch.

Round structure is what makes tier-1 reproducibility cheap: generation
is greedy and scheduler order is deterministic, prompts and replay
windows come from checkpointed ``numpy`` Generators, and checkpoints
cut at round boundaries — so a job killed mid-round and resumed from
the last checkpoint replays the exact loss trajectory the uninterrupted
job produced (``tests/test_rollout.py`` pins this under a chaos
``train.step`` kill).

A checkpoint carries BOTH model states (target trainer + draft
distiller), the served weight copies at their exact epochs, the buffer
(samples + replay rng), and the loop's own counters — everything
:meth:`RolloutRuntime.restore` needs to continue as if never
interrupted.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from ..observe import registry as _obs
from ..serve.scheduler import Request
from .buffer import RolloutBuffer, RolloutSample
from .distill import OnlineDistiller
from .publish import WeightPublisher, master_leaves

__all__ = ["RolloutRuntime"]


def _default_batch_fn(xs, weights):
    # self-training LM batch: ids are both input and labels (the loss_fn
    # shifts); staleness weights are dropped — the "drop" policy already
    # evicted anything outside the bound
    del weights
    ids = jnp.asarray(xs)
    return ids, ids


class RolloutRuntime:
    def __init__(self, engine, train_step, *,
                 buffer: Optional[RolloutBuffer] = None,
                 capacity: int = 32, max_staleness: int = 2,
                 staleness_policy: str = "drop",
                 prompt_len: int = 8, max_new_tokens: int = 8,
                 rollouts_per_round: int = 4,
                 train_batch: int = 4, train_steps_per_round: int = 2,
                 seq_len: int = 16, publish_every: int = 1,
                 distiller: Optional[OnlineDistiller] = None,
                 distill_batch: int = 4, distill_steps_per_round: int = 1,
                 distill_publish_every: int = 1,
                 batch_fn: Optional[Callable] = None,
                 prompt_fn: Optional[Callable] = None,
                 seed: int = 0):
        self.engine = engine
        self.train_step = train_step
        self.buffer = buffer if buffer is not None else RolloutBuffer(
            capacity, max_staleness=max_staleness,
            staleness_policy=staleness_policy, seed=seed + 1)
        self.publisher = WeightPublisher(engine, which="target")
        self.distiller = distiller
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.rollouts_per_round = int(rollouts_per_round)
        self.train_batch = int(train_batch)
        self.train_steps_per_round = int(train_steps_per_round)
        self.seq_len = int(seq_len)
        self.publish_every = int(publish_every)
        self.distill_batch = int(distill_batch)
        self.distill_steps_per_round = int(distill_steps_per_round)
        self.distill_publish_every = int(distill_publish_every)
        self.batch_fn = batch_fn or _default_batch_fn
        self.prompt_fn = prompt_fn or self._default_prompts
        self._vocab = int(engine.model.tok_emb.weight.shape[0])
        self._prompt_rng = np.random.default_rng(seed)
        self.round = 0
        self.losses: List[float] = []
        self.accept_windows: List[Optional[float]] = []
        self.tokens_generated = 0
        self.backpressure_rounds = 0

    # -- prompt stream -----------------------------------------------------

    def _default_prompts(self, round_idx: int, rng) -> List[Request]:
        """Seeded synthetic prompt stream.  Always draws a full round's
        worth from ``rng`` — backpressure throttles SUBMISSION, not rng
        consumption, so the stream stays aligned across a resume."""
        return [Request(rid=f"r{round_idx}.{i}",
                        prompt=[int(t) for t in
                                rng.integers(0, self._vocab,
                                             size=self.prompt_len)],
                        max_new_tokens=self.max_new_tokens)
                for i in range(self.rollouts_per_round)]

    # -- one round ---------------------------------------------------------

    def run_round(self) -> dict:
        eng = self.engine
        epoch = eng.weight_epochs["target"]
        evicted = self.buffer.evict_stale(epoch)
        reqs = self.prompt_fn(self.round, self._prompt_rng)
        n = min(len(reqs), self.buffer.free_slots)
        if n < len(reqs):
            self.backpressure_rounds += 1
            _obs.counter("rollout.backpressure").inc()
            _obs.event("rollout.backpressure", round=self.round,
                       submitted=n, throttled=len(reqs) - n,
                       buffer_fill=len(self.buffer))
        reqs = reqs[:n]
        spec0 = eng.metrics()["spec"] if eng.spec else None
        if reqs:
            eng.run(reqs)
            for rq in reqs:
                out = eng.results.pop(rq.rid)
                meta = eng.result_meta.pop(rq.rid, {})
                toks = np.concatenate(
                    [np.asarray(rq.prompt, np.int32),
                     np.asarray(out, np.int32)])
                self.tokens_generated += len(out)
                self.buffer.push(RolloutSample(
                    rid=rq.rid, tokens=toks, prompt_len=len(rq.prompt),
                    weight_epoch=meta.get("weight_epoch", epoch)))
        accept_window = None
        if eng.spec:
            spec1 = eng.metrics()["spec"]
            d_off = spec1["offered"] - spec0["offered"]
            d_acc = spec1["accepted"] - spec0["accepted"]
            accept_window = (d_acc / d_off) if d_off else None
            self.accept_windows.append(accept_window)
        round_losses: List[float] = []
        if len(self.buffer) >= self.train_batch:
            for _ in range(self.train_steps_per_round):
                xs, w, _ages = self.buffer.sample_batch(
                    self.train_batch, self.seq_len, current_epoch=epoch)
                loss = float(self.train_step(*self.batch_fn(xs, w)))
                round_losses.append(loss)
                self.losses.append(loss)
            _obs.counter("rollout.train_steps").inc(len(round_losses))
        distill_losses: List[float] = []
        if self.distiller is not None \
                and len(self.buffer) >= self.distill_batch:
            for _ in range(self.distill_steps_per_round):
                xs, _w, _ages = self.buffer.sample_batch(
                    self.distill_batch, self.seq_len, current_epoch=epoch)
                distill_losses.append(self.distiller.train_on(xs))
        self.round += 1
        if round_losses and self.round % self.publish_every == 0:
            pub = self.publisher.publish(master_leaves(self.train_step))
            _obs.gauge("rollout.weight_epoch").set(pub["epoch"])
        if self.distiller is not None and distill_losses \
                and self.round % self.distill_publish_every == 0:
            self.distiller.publish(accept_rate=accept_window)
        p50 = self.buffer.staleness_p50(eng.weight_epochs["target"])
        rec = {"round": self.round - 1, "submitted": len(reqs),
               "evicted": evicted, "losses": round_losses,
               "distill_losses": distill_losses,
               "accept_rate_window": accept_window,
               "weight_epoch": eng.weight_epochs["target"],
               "buffer_fill": len(self.buffer),
               "staleness_p50": p50}
        _obs.event("rollout.round", round=rec["round"],
                   submitted=rec["submitted"], evicted=evicted,
                   loss_last=round_losses[-1] if round_losses else None,
                   accept_rate_window=accept_window,
                   weight_epoch=rec["weight_epoch"],
                   buffer_fill=rec["buffer_fill"], staleness_p50=p50)
        return rec

    def run(self, rounds: int, *, manager=None,
            save_every: int = 1) -> List[dict]:
        """Run ``rounds`` rounds; with a
        :class:`~apex_tpu.runtime.resilience.CheckpointManager`, save
        every ``save_every`` round boundaries (the granularity a chaos
        kill can lose)."""
        recs = []
        for _ in range(int(rounds)):
            recs.append(self.run_round())
            if manager is not None and self.round % save_every == 0:
                self.save(manager)
        return recs

    # -- checkpoint --------------------------------------------------------

    def save(self, manager) -> str:
        """One atomic checkpoint of the WHOLE loop: trainer state,
        distiller state, the served weight copies at their exact
        epochs, the buffer (samples + replay rng), and loop meta."""
        serve_weights = {
            "target": [p.data for p in self.engine.model.parameters()]}
        if self.engine.spec:
            serve_weights["draft"] = [
                p.data for p in self.engine.draft.parameters()]
        meta = {
            "round": self.round,
            "epochs": dict(self.engine.weight_epochs),
            "publishes": {
                "target": self.publisher.publishes,
                "draft": (self.distiller.publisher.publishes
                          if self.distiller is not None else 0)},
            "buffer": self.buffer.state_dict(),
            "prompt_rng": self._prompt_rng.bit_generator.state,
            "losses": list(self.losses),
            "accept_windows": list(self.accept_windows),
            "tokens_generated": self.tokens_generated,
            "backpressure_rounds": self.backpressure_rounds,
        }
        comps = {"state": self.train_step.state,
                 "serve_weights": serve_weights, "rollout": meta}
        if self.distiller is not None:
            comps["draft_state"] = self.distiller.dstep.step.state
            meta["distill_losses"] = list(self.distiller.losses)
            meta["publish_log"] = [dict(r) for r in
                                   self.distiller.publish_log]
        return manager.save(self.round, **comps)

    def restore(self, manager) -> Optional[int]:
        """Resume from the newest VALID checkpoint (corrupt ones are
        scanned past, ``restore_or_initialize`` semantics).  Re-devices
        the trainer state under its current layout, republishes the
        saved serve weights at their SAVED epochs (bit-exact), reloads
        the buffer and both rngs, and rewinds the loop counters.
        Returns the checkpoint's round number, or None on a fresh
        start."""
        step_no, comps = manager.restore_or_initialize()
        if step_no is None:
            return None
        self.train_step.load_state(comps["state"])
        meta = comps["rollout"]
        sw = comps["serve_weights"]
        self.publisher.restore(sw["target"],
                               epoch=int(meta["epochs"]["target"]))
        self.publisher.publishes = int(meta["publishes"]["target"])
        if self.distiller is not None:
            self.distiller.dstep.step.load_state(comps["draft_state"])
            self.distiller.publisher.restore(
                sw["draft"], epoch=int(meta["epochs"]["draft"]))
            self.distiller.publisher.publishes = \
                int(meta["publishes"]["draft"])
            self.distiller.losses = list(meta.get("distill_losses", []))
            self.distiller.publish_log = [
                dict(r) for r in meta.get("publish_log", [])]
        self.buffer.load_state_dict(meta["buffer"])
        self._prompt_rng.bit_generator.state = meta["prompt_rng"]
        self.round = int(meta["round"])
        self.losses = [float(x) for x in meta["losses"]]
        self.accept_windows = list(meta["accept_windows"])
        self.tokens_generated = int(meta["tokens_generated"])
        self.backpressure_rounds = int(meta["backpressure_rounds"])
        _obs.event("rollout.restore", round=self.round,
                   weight_epoch=self.engine.weight_epochs["target"],
                   buffer_fill=len(self.buffer))
        return step_no
