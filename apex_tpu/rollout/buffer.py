"""Bounded-staleness rollout buffer with deterministic seeded replay.

The buffer is the seam between the serve side (producing finished
continuations) and the train side (consuming token windows): a FIFO of
:class:`RolloutSample` records, each stamped with the weight epoch its
generation was ADMITTED under (the oldest weights any of its tokens
saw — the engine stamps it, see ``ServeEngine.publish_weights``).

Staleness is measured in weight epochs, not wall time: a sample's age
is ``current_epoch - sample.weight_epoch``.  Two policies bound it:

* ``"drop"`` (default) — :meth:`evict_stale` removes samples older
  than ``max_staleness`` before each round; evictions are counted and
  emitted (``rollout.evicted_stale``).
* ``"downweight"`` — nothing is evicted; :meth:`sample_batch` returns
  per-sample loss weights ``downweight ** (age - max_staleness)``
  (1.0 within the bound) for the caller to fold into its loss.

Backpressure is the CALLER's half of the contract: the runtime reserves
``free_slots`` before submitting prompts, so :meth:`push` never drops a
finished rollout — a full buffer throttles generation instead
(``rollout.backpressure`` counts the throttled rounds).  ``push`` still
refuses when full (counted) so a caller that skips the reservation
fails loudly in its metrics rather than silently growing memory.

Replay is seeded and fully checkpointable: :meth:`sample_batch` draws
through a private ``numpy`` Generator whose bit-generator state rides
in :meth:`state_dict`, so a restored buffer replays the exact batch
sequence the uninterrupted run would have drawn — the loss-trajectory
reproducibility pin of tier-1 rests on this.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from ..observe import registry as _obs

__all__ = ["RolloutSample", "RolloutBuffer"]

_POLICIES = ("drop", "downweight")


@dataclass
class RolloutSample:
    """One finished rollout: prompt + generated ids, flat int32."""
    rid: str
    tokens: np.ndarray           # 1-D int32, prompt then continuation
    prompt_len: int
    weight_epoch: int            # target epoch at admission

    def __post_init__(self):
        self.tokens = np.asarray(self.tokens, np.int32).reshape(-1)
        self.prompt_len = int(self.prompt_len)
        self.weight_epoch = int(self.weight_epoch)


class RolloutBuffer:
    def __init__(self, capacity: int, *, max_staleness: int = 2,
                 staleness_policy: str = "drop", downweight: float = 0.5,
                 seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if staleness_policy not in _POLICIES:
            raise ValueError(f"staleness_policy must be one of "
                             f"{_POLICIES}, got {staleness_policy!r}")
        self.capacity = int(capacity)
        self.max_staleness = int(max_staleness)
        self.staleness_policy = staleness_policy
        self.downweight = float(downweight)
        self._samples: List[RolloutSample] = []
        self._rng = np.random.default_rng(seed)
        self.pushed = 0
        self.rejects = 0
        self.evicted = 0
        self.draws = 0

    def __len__(self) -> int:
        return len(self._samples)

    @property
    def free_slots(self) -> int:
        return self.capacity - len(self._samples)

    # -- produce -----------------------------------------------------------

    def push(self, sample: RolloutSample) -> bool:
        """Append a finished rollout; False (counted) when full — the
        runtime's slot reservation makes this unreachable in the loop."""
        if len(self._samples) >= self.capacity:
            self.rejects += 1
            _obs.counter("rollout.buffer.rejects").inc()
            return False
        self._samples.append(sample)
        self.pushed += 1
        _obs.counter("rollout.samples").inc()
        _obs.gauge("rollout.buffer_fill").set(len(self._samples))
        return True

    # -- staleness ---------------------------------------------------------

    def ages(self, current_epoch: int) -> List[int]:
        return [current_epoch - s.weight_epoch for s in self._samples]

    def staleness_p50(self, current_epoch: int) -> float:
        ages = self.ages(current_epoch)
        return float(np.median(ages)) if ages else 0.0

    def evict_stale(self, current_epoch: int) -> int:
        """Drop samples older than ``max_staleness`` epochs (no-op under
        the downweight policy).  Returns the eviction count."""
        if self.staleness_policy != "drop":
            return 0
        keep = [s for s in self._samples
                if current_epoch - s.weight_epoch <= self.max_staleness]
        n = len(self._samples) - len(keep)
        if n:
            self._samples = keep
            self.evicted += n
            _obs.counter("rollout.evicted_stale").inc(n)
            _obs.gauge("rollout.buffer_fill").set(len(self._samples))
        return n

    # -- consume -----------------------------------------------------------

    def sample_batch(self, batch_size: int, seq_len: int, *,
                     current_epoch: int) -> Tuple[np.ndarray, np.ndarray,
                                                  np.ndarray]:
        """Draw ``batch_size`` fixed-length token windows (seeded, with
        replacement; short rollouts tile deterministically via
        ``np.resize``).  Returns ``(ids (B,S) int32, weights (B,) f32,
        ages (B,) int)`` — weights are all-ones under ``"drop"`` and the
        staleness decay under ``"downweight"``."""
        if not self._samples:
            raise ValueError("sample_batch on an empty RolloutBuffer")
        idx = self._rng.integers(0, len(self._samples), size=batch_size)
        xs = np.stack([np.resize(self._samples[i].tokens, seq_len)
                       for i in idx]).astype(np.int32)
        ages = np.array([current_epoch - self._samples[i].weight_epoch
                         for i in idx], np.int64)
        if self.staleness_policy == "downweight":
            over = np.maximum(ages - self.max_staleness, 0)
            w = (self.downweight ** over).astype(np.float32)
        else:
            w = np.ones(batch_size, np.float32)
        self.draws += 1
        for a in ages:
            _obs.histogram("rollout.staleness").observe(float(a))
        return xs, w, ages

    # -- checkpoint --------------------------------------------------------

    def state_dict(self) -> Dict:
        """Everything a bit-exact resume needs: samples, counters, and
        the replay Generator's bit-generator state."""
        return {
            "samples": [{"rid": s.rid, "tokens": s.tokens.copy(),
                         "prompt_len": s.prompt_len,
                         "weight_epoch": s.weight_epoch}
                        for s in self._samples],
            "rng": self._rng.bit_generator.state,
            "counters": {"pushed": self.pushed, "rejects": self.rejects,
                         "evicted": self.evicted, "draws": self.draws},
            "config": {"capacity": self.capacity,
                       "max_staleness": self.max_staleness,
                       "staleness_policy": self.staleness_policy,
                       "downweight": self.downweight},
        }

    def load_state_dict(self, sd: Dict) -> "RolloutBuffer":
        cfg = sd.get("config", {})
        if cfg and int(cfg["capacity"]) != self.capacity:
            raise ValueError(
                f"rollout buffer capacity mismatch: checkpoint has "
                f"{cfg['capacity']}, this buffer {self.capacity} — "
                f"replay would diverge")
        self._samples = [RolloutSample(**s) for s in sd["samples"]]
        self._rng.bit_generator.state = sd["rng"]
        c = sd.get("counters", {})
        self.pushed = int(c.get("pushed", 0))
        self.rejects = int(c.get("rejects", 0))
        self.evicted = int(c.get("evicted", 0))
        self.draws = int(c.get("draws", 0))
        return self
