from .multi_tensor import (
    ADAM_MODE_DECOUPLED,
    ADAM_MODE_L2,
    multi_tensor_adam,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_lamb,
    multi_tensor_maxnorm,
    multi_tensor_novograd,
    multi_tensor_scale,
    multi_tensor_sgd,
    zero_flag,
)

__all__ = [
    "ADAM_MODE_DECOUPLED",
    "ADAM_MODE_L2",
    "multi_tensor_adam",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "multi_tensor_lamb",
    "multi_tensor_maxnorm",
    "multi_tensor_novograd",
    "multi_tensor_scale",
    "multi_tensor_sgd",
    "zero_flag",
]
