"""Pallas kernel substrate — backend selection for the fused TPU kernels.

The reference ships each fused op twice: a CUDA extension and a pure-Python
fallback chosen at import time (e.g. apex/parallel/__init__.py:14-19,
apex/multi_tensor_apply/multi_tensor_apply.py:3-30 ``available``).  Our
analogue is trace-time dispatch: on TPU the Pallas kernel compiles natively;
elsewhere ops fall back to an equivalent pure-jnp path (same numerics — this
duality is also the test oracle, mirroring tests/L1 "extension build vs
python build" loss comparison).  ``interpret`` mode runs the actual Pallas
kernels through the interpreter on CPU so kernel logic is testable without
hardware.
"""
import contextlib
import os

import jax

_forced = [None]


def pallas_mode():
    """Returns 'compiled' | 'interpret' | None (use the jnp fallback).

    Priority: force_mode() context > APEX_TPU_PALLAS env var
    ('off'/'0', 'interpret', 'compiled') > backend autodetect.
    """
    if _forced[0] is not None:
        return None if _forced[0] == "off" else _forced[0]
    env = os.environ.get("APEX_TPU_PALLAS", "").lower()
    if env in ("0", "off"):
        return None
    if env in ("interpret", "compiled"):
        return env
    return "compiled" if jax.default_backend() == "tpu" else None


@contextlib.contextmanager
def force_mode(mode):
    """Force kernel dispatch for a scope: 'compiled', 'interpret' or 'off'.

    Note: dispatch happens at trace time, so already-jitted callables keep
    the mode they were traced with.
    """
    prev = _forced[0]
    _forced[0] = mode
    try:
        yield
    finally:
        _forced[0] = prev


# The masked-vocabulary convention, in one place: logits at MASKED_FILL
# (-1e30) mean "this column does not exist" (lane-padded heads'
# pad columns, nucleus-filtered tokens); consumers treat anything at or
# below MASKED_LOGIT_THR (-1e29) as masked — softmax contributions
# underflow to 0 there, and the smoothing-aware losses
# (nn.functional.cross_entropy, contrib.xentropy) exclude such columns
# from the label-smoothing term and its divisor.
MASKED_FILL = -1e30
MASKED_LOGIT_THR = -1e29
