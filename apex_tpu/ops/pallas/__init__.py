"""Compatibility shim — the Pallas kernels moved to
:mod:`apex_tpu.kernels` (the measured kernel tier with dispatch policy
and calibration ledger).

This package re-exports the dispatch surface the old location provided
(``pallas_mode``/``force_mode``/``norm_kernel_mode`` and the
masked-vocabulary constants) and aliases the old submodule paths
(``apex_tpu.ops.pallas.attention`` etc.) onto the moved modules, so
existing ``from apex_tpu.ops.pallas.attention import ...`` imports keep
resolving to the SAME module objects.  New code should import from
:mod:`apex_tpu.kernels` directly.
"""
from __future__ import annotations

import sys

from ...kernels import (attention, layer_norm, lm_head_xent, rms_norm,
                        xentropy)
from ...kernels.dispatch import (  # noqa: F401
    MASKED_FILL,
    MASKED_LOGIT_THR,
    force_mode,
    norm_kernel_mode,
    pallas_mode,
)

for _name, _mod in (("attention", attention), ("layer_norm", layer_norm),
                    ("rms_norm", rms_norm), ("xentropy", xentropy),
                    ("lm_head_xent", lm_head_xent)):
    sys.modules[__name__ + "." + _name] = _mod
del _name, _mod

__all__ = [
    "MASKED_FILL",
    "MASKED_LOGIT_THR",
    "attention",
    "force_mode",
    "layer_norm",
    "lm_head_xent",
    "norm_kernel_mode",
    "pallas_mode",
    "rms_norm",
    "xentropy",
]
