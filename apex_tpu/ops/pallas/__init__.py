"""Pallas kernel substrate — backend selection for the fused TPU kernels.

The reference ships each fused op twice: a CUDA extension and a pure-Python
fallback chosen at import time (e.g. apex/parallel/__init__.py:14-19,
apex/multi_tensor_apply/multi_tensor_apply.py:3-30 ``available``).  Our
analogue is trace-time dispatch: on TPU the Pallas kernel compiles natively;
elsewhere ops fall back to an equivalent pure-jnp path (same numerics — this
duality is also the test oracle, mirroring tests/L1 "extension build vs
python build" loss comparison).  ``interpret`` mode runs the actual Pallas
kernels through the interpreter on CPU so kernel logic is testable without
hardware.
"""
import contextlib
import os

import jax

_forced = [None]


def pallas_mode():
    """Returns 'compiled' | 'interpret' | None (use the jnp fallback).

    Priority: force_mode() context > APEX_TPU_PALLAS env var
    ('off'/'0', 'interpret', 'compiled') > backend autodetect.
    """
    if _forced[0] is not None:
        return None if _forced[0] == "off" else _forced[0]
    env = os.environ.get("APEX_TPU_PALLAS", "").lower()
    if env in ("0", "off"):
        return None
    if env in ("interpret", "compiled"):
        return env
    return "compiled" if jax.default_backend() == "tpu" else None


@contextlib.contextmanager
def force_mode(mode):
    """Force kernel dispatch for a scope: 'compiled', 'interpret' or 'off'.

    Note: dispatch happens at trace time, so already-jitted callables keep
    the mode they were traced with.
    """
    prev = _forced[0]
    _forced[0] = mode
    try:
        yield
    finally:
        _forced[0] = prev


# The masked-vocabulary convention, in one place: logits at MASKED_FILL
# (-1e30) mean "this column does not exist" (lane-padded heads'
# pad columns, nucleus-filtered tokens); consumers treat anything at or
# below MASKED_LOGIT_THR (-1e29) as masked — softmax contributions
# underflow to 0 there, and the smoothing-aware losses
# (nn.functional.cross_entropy, contrib.xentropy) exclude such columns
# from the label-smoothing term and its divisor.
MASKED_FILL = -1e30
MASKED_LOGIT_THR = -1e29


# Round-5 norm-kernel verdict (BENCH_HISTORY round 5).  The
# variance-controlled isolated A/B (median of 5 interleaved reps)
# put every LN/RMS row in a 0.93-1.03x band around XLA's own fusion —
# the round-3 "1.73x LN win" was single-run noise — and the IN-STEP
# A/B then showed routing norms to XLA is a real headline win:
# BERT 1178->1252 (+6.3%), GPT 1044->1067 (+2.2%), Llama 1396->1469
# (+5.2%) seq/s.  A Pallas custom call is a fusion barrier; XLA fuses
# the norm into its producers/consumers when allowed to own it.
# Default therefore defers to XLA on compiled TPU; the kernels stay
# for interpret-mode parity coverage and APEX_TPU_NORM_KERNEL=1 opts
# back in on-chip.
_NORM_KERNEL_DEFAULT_ON = False


def norm_kernel_mode():
    """Effective dispatch mode for the LayerNorm/RMSNorm Pallas
    kernels: ``pallas_mode()`` gated by APEX_TPU_NORM_KERNEL
    ('auto'/'1'/'0') on compiled backends.  A ``force_mode`` scope
    overrides the gate (parity checks and tests force the kernel arm
    explicitly and must never silently self-compare); interpret mode
    always exercises the kernels — that mode exists to test them."""
    if _forced[0] is not None:
        return pallas_mode()
    mode = pallas_mode()
    if mode != "compiled":
        return mode
    env = os.environ.get("APEX_TPU_NORM_KERNEL", "auto").lower()
    if env in ("1", "on"):
        return mode
    if env in ("0", "off"):
        return None
    return mode if _NORM_KERNEL_DEFAULT_ON else None
