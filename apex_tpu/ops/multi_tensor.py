"""Multi-tensor fused-op suite: the TPU-native equivalent of Apex's ``amp_C``
extension (reference: /root/reference/csrc/amp_C_frontend.cpp:100-119 and the
``multi_tensor_*_kernel.cu`` family).

Design notes (TPU-first, not a port):

The CUDA reference packs up to 110 raw tensor pointers into kernel launch
metadata (``csrc/multi_tensor_apply.cuh:15-130``) so a whole parameter group is
processed in a handful of launches.  Under XLA there are no launches to
amortise: each op here is a pure, jittable function over *lists* of
``jax.Array``; XLA fuses the per-tensor elementwise work into a small number of
fused loops and the whole optimizer step is usually a single executable.  The
observable semantics preserved from the reference:

* a ``noop_flag`` overflow sentinel: ``multi_tensor_scale``/``axpby`` set it on
  any non-finite value (``multi_tensor_scale_kernel.cu:69-72``) — here an
  ``int32`` scalar on device, OR-accumulated functionally.  The optimizer ops
  never *write* it (the reference kernels deliberately propagate infs/nans,
  ``multi_tensor_adam.cu:40-41``); only ``multi_tensor_sgd`` *reads* it and
  leaves params/momenta untouched when set
  (``multi_tensor_sgd_kernel.cu:46``);
* fp32 math (``MATH_T``) regardless of fp16/bf16 storage
  (``csrc/multi_tensor_adam.cu`` uses float accumulators);
* in/out dtype cross-products (fp16/bf16/fp32 in → fp16/bf16/fp32 out).

Everything returns new arrays (functional); stateful wrappers in
``apex_tpu.optimizers`` / ``apex_tpu.amp`` rebind them.
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp

_f32 = jnp.float32


def _nonfinite(x) -> jax.Array:
    """True (int32 1) if any element of x is non-finite."""
    return (~jnp.isfinite(x.astype(_f32))).any().astype(jnp.int32)


def _static_nonzero(x) -> bool:
    """Whether a scalar hyperparameter must enter the program.

    False only for a concrete Python zero; traced device scalars (the step
    cache passes lr/wd/betas as traced f32 so schedules never retrace)
    always count as nonzero and the term compiles in — multiplying by a
    runtime 0.0 is then a numeric no-op.
    """
    return not (isinstance(x, (int, float)) and x == 0.0)


def _or_flags(noop_flag, flags):
    out = noop_flag
    for f in flags:
        out = jnp.maximum(out, f)
    return out


def zero_flag() -> jax.Array:
    """Fresh overflow sentinel (the reference's ``_overflow_buf.zero_()``)."""
    return jnp.zeros((), jnp.int32)


# ---------------------------------------------------------------------------
# multi_tensor_scale — csrc/multi_tensor_scale_kernel.cu:18-101
# ---------------------------------------------------------------------------

def multi_tensor_scale(noop_flag, tensor_lists: Sequence[Sequence[jax.Array]],
                       scale):
    """out[i] = in[i] * scale, flagging non-finite inputs.

    ``tensor_lists = [ins, outs]``; ``outs`` supplies the output dtypes
    (the fp16/fp32 cross-product of the reference kernel).  Returns
    ``(noop_flag, new_outs)``.
    """
    ins, outs = tensor_lists
    new_outs, flags = [], []
    for x, o in zip(ins, outs):
        xf = x.astype(_f32)
        y = xf * jnp.asarray(scale, _f32)
        flags.append(_nonfinite(x))
        new_outs.append(y.astype(o.dtype))
    return _or_flags(noop_flag, flags), new_outs


# ---------------------------------------------------------------------------
# multi_tensor_axpby — csrc/multi_tensor_axpby_kernel.cu
# ---------------------------------------------------------------------------

def multi_tensor_axpby(noop_flag, tensor_lists, a, b, arg_to_check: int = -1):
    """out = a*x + b*y with overflow check on x (0), y (1) or both (-1)
    (reference: csrc/amp_C_frontend.cpp:22-28)."""
    xs, ys, outs = tensor_lists
    new_outs, flags = [], []
    for x, y, o in zip(xs, ys, outs):
        r = jnp.asarray(a, _f32) * x.astype(_f32) + jnp.asarray(b, _f32) * y.astype(_f32)
        if arg_to_check == 0:
            flags.append(_nonfinite(x))
        elif arg_to_check == 1:
            flags.append(_nonfinite(y))
        else:
            flags.append(jnp.maximum(_nonfinite(x), _nonfinite(y)))
        new_outs.append(r.astype(o.dtype))
    return _or_flags(noop_flag, flags), new_outs


# ---------------------------------------------------------------------------
# multi_tensor_l2norm — csrc/multi_tensor_l2norm_kernel.cu
# ---------------------------------------------------------------------------

def multi_tensor_l2norm(noop_flag, tensor_lists, per_tensor: bool = False):
    """Returns (noop_flag, total_l2_norm, per_tensor_norms-or-None).

    The reference runs a two-stage block reduction plus ``cleanup`` kernel;
    XLA's reduction codegen replaces all of that.
    """
    (xs,) = tensor_lists
    if not xs:
        z = jnp.zeros((), _f32)
        return noop_flag, z, (jnp.zeros((0,), _f32) if per_tensor else None)
    sqs = [jnp.sum(jnp.square(x.astype(_f32))) for x in xs]
    total = jnp.sqrt(functools.reduce(jnp.add, sqs))
    per = jnp.sqrt(jnp.stack(sqs)) if per_tensor else None
    return noop_flag, total, per


def multi_tensor_maxnorm(noop_flag, tensor_lists, per_tensor: bool = False):
    """Max-abs-norm variant (csrc/multi_tensor_l2norm_kernel.cu:80)."""
    (xs,) = tensor_lists
    if not xs:
        z = jnp.zeros((), _f32)
        return noop_flag, z, (jnp.zeros((0,), _f32) if per_tensor else None)
    ms = [jnp.max(jnp.abs(x.astype(_f32))) for x in xs]
    total = functools.reduce(jnp.maximum, ms)
    per = jnp.stack(ms) if per_tensor else None
    return noop_flag, total, per


# ---------------------------------------------------------------------------
# multi_tensor_sgd — csrc/multi_tensor_sgd_kernel.cu:29-278
# ---------------------------------------------------------------------------

def _use_fused(op: str, tensor_lists) -> bool:
    """Whether the dispatch policy routes this group to the packed
    Pallas kernel (apex_tpu.kernels.multi_tensor).  Trace-time static:
    consults the calibration ledger through kernels.dispatch — on CPU
    without a forced mode this is always False and the per-bucket
    path below runs unchanged."""
    if not tensor_lists or not tensor_lists[0]:
        return False
    from ..kernels import dispatch as _dispatch
    from ..kernels.multi_tensor import group_fp
    name = f"multi_tensor_{op}"
    return _dispatch.decide(name, group_fp(op, tensor_lists[0])).tier \
        == "pallas"


def multi_tensor_sgd(noop_flag, tensor_lists, wd, momentum, dampening, lr,
                     nesterov: bool, first_run: bool, wd_after_momentum: bool,
                     scale=1.0):
    """Momentum SGD over lists — dispatch-gated between the per-bucket
    stacks (:func:`sgd_unfused`) and the packed Pallas kernel
    (:func:`apex_tpu.kernels.multi_tensor.fused_sgd`); see
    :func:`sgd_unfused` for the update semantics."""
    if _use_fused("sgd", tensor_lists):
        from ..kernels.multi_tensor import fused_sgd
        return fused_sgd(noop_flag, tensor_lists, wd, momentum, dampening,
                         lr, nesterov, first_run, wd_after_momentum, scale)
    return sgd_unfused(noop_flag, tensor_lists, wd, momentum, dampening,
                       lr, nesterov, first_run, wd_after_momentum, scale)


def sgd_unfused(noop_flag, tensor_lists, wd, momentum, dampening, lr,
                nesterov: bool, first_run: bool, wd_after_momentum: bool,
                scale=1.0):
    """Momentum SGD over lists.

    depth 3: ``[grads, params, momenta]`` — returns (flag, params, momenta)
    depth 4: ``[grads, master_params, momenta, model_params]`` — additionally
    writes the fp16/bf16 model copy in the same pass
    (csrc/multi_tensor_sgd_kernel.cu:14-28).  ``scale`` folds gradient
    unscaling into the update (FusedSGD + amp integration,
    apex/optimizers/fused_sgd.py:211-215).

    Honors an already-set incoming ``noop_flag``: the whole update is skipped
    and inputs are returned unchanged, matching the reference kernel's
    ``if (*noop_gmem) return;`` early exit (multi_tensor_sgd_kernel.cu:46).
    """
    depth = len(tensor_lists)
    if depth == 3:
        gs, ps, ms = tensor_lists
        model_ps = None
    elif depth == 4:
        gs, ps, ms, model_ps = tensor_lists
    else:
        raise ValueError(f"multi_tensor_sgd supports depth 3 or 4, got {depth}")

    lr = jnp.asarray(lr, _f32)
    skip = noop_flag > 0
    new_ps, new_ms, new_model = [], [], []
    for i, (g, p, m) in enumerate(zip(gs, ps, ms)):
        gf = g.astype(_f32) * jnp.asarray(scale, _f32)
        pf = p.astype(_f32)
        mf = m.astype(_f32)
        if _static_nonzero(wd) and not wd_after_momentum:
            gf = gf + wd * pf
        if momentum != 0.0:
            if first_run:
                mf = gf
            else:
                mf = momentum * mf + (1.0 - dampening) * gf
            upd = gf + momentum * mf if nesterov else mf
        else:
            upd = gf
        if _static_nonzero(wd) and wd_after_momentum:
            upd = upd + wd * pf
        pf = pf - lr * upd
        new_ps.append(jnp.where(skip, p, pf.astype(p.dtype)))
        new_ms.append(jnp.where(skip, m, mf.astype(m.dtype)))
        if model_ps is not None:
            new_model.append(jnp.where(skip, model_ps[i],
                                       pf.astype(model_ps[i].dtype)))
    if model_ps is not None:
        return noop_flag, new_ps, new_ms, new_model
    return noop_flag, new_ps, new_ms


# ---------------------------------------------------------------------------
# multi_tensor_adam — csrc/multi_tensor_adam.cu
# ---------------------------------------------------------------------------

ADAM_MODE_L2 = 0          # L2 regularisation (classic Adam)
ADAM_MODE_DECOUPLED = 1   # AdamW decoupled weight decay


def multi_tensor_adam(noop_flag, tensor_lists, lr, beta1, beta2, eps, step,
                      mode: int, bias_correction: bool, weight_decay):
    """Adam / AdamW over lists — dispatch-gated between the per-bucket
    stacks (:func:`adam_unfused`) and the packed Pallas kernel
    (:func:`apex_tpu.kernels.multi_tensor.fused_adam`); see
    :func:`adam_unfused` for the update semantics."""
    if _use_fused("adam", tensor_lists):
        from ..kernels.multi_tensor import fused_adam
        return fused_adam(noop_flag, tensor_lists, lr, beta1, beta2, eps,
                          step, mode, bias_correction, weight_decay)
    return adam_unfused(noop_flag, tensor_lists, lr, beta1, beta2, eps,
                        step, mode, bias_correction, weight_decay)


def adam_unfused(noop_flag, tensor_lists, lr, beta1, beta2, eps, step,
                 mode: int, bias_correction: bool, weight_decay):
    """Adam / AdamW over ``[grads, params, exp_avgs, exp_avg_sqs]``.

    Bias correction is computed host-side exactly as the reference does
    (csrc/multi_tensor_adam.cu:144-149) when ``step`` is a Python int, and
    on-device otherwise (so the whole train step can stay jitted).

    Like the reference kernel, deliberately propagates infs/nans rather than
    writing the noop flag (multi_tensor_adam.cu:40-41) — overflow handling is
    the loss scaler's job.
    """
    gs, ps, ms, vs = tensor_lists
    if bias_correction:
        if isinstance(step, (int, float)):
            bc1 = 1.0 - beta1 ** step
            bc2 = 1.0 - beta2 ** step
        else:
            stepf = jnp.asarray(step, _f32)
            bc1 = 1.0 - jnp.asarray(beta1, _f32) ** stepf
            bc2 = 1.0 - jnp.asarray(beta2, _f32) ** stepf
    else:
        bc1 = bc2 = 1.0
    lr = jnp.asarray(lr, _f32)

    new_ps, new_ms, new_vs = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        gf, pf = g.astype(_f32), p.astype(_f32)
        mf, vf = m.astype(_f32), v.astype(_f32)
        if mode == ADAM_MODE_L2 and _static_nonzero(weight_decay):
            gf = gf + weight_decay * pf
        mf = beta1 * mf + (1.0 - beta1) * gf
        vf = beta2 * vf + (1.0 - beta2) * gf * gf
        update = (mf / bc1) / (jnp.sqrt(vf / bc2) + eps)
        if mode == ADAM_MODE_DECOUPLED and _static_nonzero(weight_decay):
            update = update + weight_decay * pf
        pf = pf - lr * update
        new_ps.append(pf.astype(p.dtype))
        new_ms.append(mf.astype(m.dtype))
        new_vs.append(vf.astype(v.dtype))
    return noop_flag, new_ps, new_ms, new_vs


# ---------------------------------------------------------------------------
# multi_tensor_novograd — csrc/multi_tensor_novograd.cu
# ---------------------------------------------------------------------------

NOVOGRAD_MOMENT_MODE_0 = 0   # L2 on grad: g' = g/denom + wd*p folded into momentum
NOVOGRAD_MOMENT_MODE_1 = 1   # decoupled: m on raw grads, wd*p added to update


def multi_tensor_novograd(noop_flag, tensor_lists, lr, beta1, beta2, eps, step,
                          bias_correction: bool, weight_decay, grad_averaging: int,
                          moment_mode: int, norm_type: int):
    """NovoGrad over ``[grads, params, exp_avgs, grad_norms]`` where
    ``grad_norms`` holds one running second-moment norm scalar per tensor
    (apex/optimizers/fused_novograd.py:106-172).

    Norm blend (csrc/multi_tensor_novograd.cu:160-164 →
    multi_tensor_l2norm_kernel.cu cleanup_v2:198-207):
      L-2 (norm_type=2):   gn = sqrt(beta2*gn² + (1-beta2)*‖g‖²)
      L-inf (norm_type=0): gn = beta2*gn + (1-beta2)*max|g|
    Moment modes (multi_tensor_novograd.cu:97-112):
      MODE_0: g' = g/denom + wd*p;  m = b1*m + b3*g';  p -= lr*(m/bc1)
      MODE_1: m = b1*m + b3*g;      p -= lr*((m/bc1)/denom + wd*p)
    with denom = gn/bc2 + eps and bc2 = sqrt(1-beta2^step)
    (multi_tensor_novograd.cu:150-151).

    Returns (flag, new_params, new_exp_avgs, new_grad_norms).  Like the
    reference kernel, propagates infs/nans instead of writing the flag.
    """
    gs, ps, ms, grad_norms = tensor_lists
    if bias_correction:
        if isinstance(step, (int, float)):
            bc1 = 1.0 - beta1 ** step
            bc2 = (1.0 - beta2 ** step) ** 0.5
        else:
            stepf = jnp.asarray(step, _f32)
            bc1 = 1.0 - jnp.asarray(beta1, _f32) ** stepf
            bc2 = jnp.sqrt(1.0 - jnp.asarray(beta2, _f32) ** stepf)
    else:
        bc1 = bc2 = 1.0
    beta3 = (1.0 - beta1) if grad_averaging else 1.0
    lr = jnp.asarray(lr, _f32)

    new_ps, new_ms, new_norms = [], [], []
    for g, p, m, vn in zip(gs, ps, ms, grad_norms):
        gf, pf, mf = g.astype(_f32), p.astype(_f32), m.astype(_f32)
        if norm_type == 0:  # L-inf: linear blend, NOT a running max
            local = jnp.max(jnp.abs(gf))
            gn = beta2 * vn.astype(_f32) + (1.0 - beta2) * local
        else:  # L2
            local = jnp.sum(gf * gf)
            gn = jnp.sqrt(beta2 * jnp.square(vn.astype(_f32))
                          + (1.0 - beta2) * local)
        denom = gn / bc2 + eps
        if moment_mode == NOVOGRAD_MOMENT_MODE_0:
            gprime = gf / denom + weight_decay * pf
            mf = beta1 * mf + beta3 * gprime
            pf = pf - lr * (mf / bc1)
        else:
            mf = beta1 * mf + beta3 * gf
            update = (mf / bc1) / denom + weight_decay * pf
            pf = pf - lr * update
        new_ps.append(pf.astype(p.dtype))
        new_ms.append(mf.astype(m.dtype))
        new_norms.append(gn.astype(vn.dtype))
    return noop_flag, new_ps, new_ms, new_norms


# ---------------------------------------------------------------------------
# multi_tensor_lamb — csrc/multi_tensor_lamb.cu
# ---------------------------------------------------------------------------

def multi_tensor_lamb(noop_flag, tensor_lists, lr, beta1, beta2, eps, step,
                      bias_correction: bool, weight_decay, grad_averaging: int,
                      mode: int, global_grad_norm, max_grad_norm):
    """Fused LAMB over ``[grads, params, exp_avgs, exp_avg_sqs]``.

    Stage 1 (csrc/multi_tensor_lamb.cu:30-55): Adam-style update ``u`` with
    global gradient-norm clipping
    (``clipped = gnorm > max ? gnorm/max : 1``, :55).
    Stage 2 (:144-166): per-tensor trust ratio — ``ratio = lr*(‖p‖/‖u‖)``
    when both norms are nonzero, else plain ``lr`` — applied as
    ``p -= ratio * u``.  ``mode``: 0 = L2 wd inside moment update,
    1 = AdamW-style decoupled.  Propagates infs/nans (no flag writes),
    matching the commented-out noop checks at :48,:156.
    """
    gs, ps, ms, vs = tensor_lists
    if bias_correction:
        bc1 = 1.0 - beta1 ** step if isinstance(step, (int, float)) else \
            1.0 - jnp.asarray(beta1, _f32) ** jnp.asarray(step, _f32)
        bc2 = 1.0 - beta2 ** step if isinstance(step, (int, float)) else \
            1.0 - jnp.asarray(beta2, _f32) ** jnp.asarray(step, _f32)
    else:
        bc1 = bc2 = 1.0
    beta3 = (1.0 - beta1) if grad_averaging else 1.0
    lr = jnp.asarray(lr, _f32)
    gnorm = jnp.asarray(global_grad_norm, _f32)
    if max_grad_norm is not None and max_grad_norm > 0:
        clip = jnp.where(gnorm > max_grad_norm, gnorm / max_grad_norm,
                         jnp.asarray(1.0, _f32))
    else:
        clip = jnp.asarray(1.0, _f32)

    new_ps, new_ms, new_vs = [], [], []
    for g, p, m, v in zip(gs, ps, ms, vs):
        gf = g.astype(_f32) / clip
        pf, mf, vf = p.astype(_f32), m.astype(_f32), v.astype(_f32)
        if mode == ADAM_MODE_L2 and _static_nonzero(weight_decay):
            gf = gf + weight_decay * pf
        mf = beta1 * mf + beta3 * gf
        vf = beta2 * vf + (1.0 - beta2) * gf * gf
        u = (mf / bc1) / (jnp.sqrt(vf / bc2) + eps)
        if mode == ADAM_MODE_DECOUPLED and _static_nonzero(weight_decay):
            u = u + weight_decay * pf
        # stage 2: trust ratio (multi_tensor_lamb.cu:166)
        p_norm = jnp.sqrt(jnp.sum(pf * pf))
        u_norm = jnp.sqrt(jnp.sum(u * u))
        use_ratio = (p_norm != 0) & (u_norm != 0)
        ratio = jnp.where(use_ratio,
                          lr * p_norm / jnp.where(use_ratio, u_norm, 1.0), lr)
        pf = pf - ratio * u
        new_ps.append(pf.astype(p.dtype))
        new_ms.append(mf.astype(m.dtype))
        new_vs.append(vf.astype(v.dtype))
    return noop_flag, new_ps, new_ms, new_vs
