"""Dispatcher mirroring apex/multi_tensor_apply/multi_tensor_apply.py:3-30.

The reference feeds a chunk size, an overflow buffer and tensor lists to a
CUDA op.  Here the "ops" are the pure functions in ``apex_tpu.ops.multi_tensor``
and chunking is XLA's job, so ``chunk_size`` is accepted and ignored (kept for
API parity).  Unlike the reference there is no extension to fail to import, so
``available`` is always True; the flag is kept because downstream code in the
reference checks it (e.g. apex/amp/scaler.py) and users may too.
"""


class MultiTensorApply:
    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size
        self._record = None

    def __call__(self, op, noop_flag_buffer, tensor_lists, *args, **kwargs):
        # launch-count observability: the step cache's stats() reports these
        # as the analogue of the reference's per-step kernel-launch count
        if self._record is None:
            from ..runtime.step_cache import record_multi_tensor_call
            self._record = record_multi_tensor_call
        self._record()
        return op(noop_flag_buffer, tensor_lists, *args, **kwargs)


multi_tensor_applier = MultiTensorApply(2048 * 32)
