"""Pallas TPU RMS-norm kernels.

RMSNorm (Zhang & Sennrich 2019) is the LayerNorm variant modern LLM
families (Llama et al.) use: no mean subtraction, no bias — the saved
residual is just the fp32 reciprocal RMS per row.  Same kernel layout as
layer_norm.py (the reference analogue is ``fused_layer_norm_cuda``,
csrc/layer_norm_cuda.cpp — the reference has no RMS variant; this one
exists for the Llama family): rows blocked over a 1-D sequential grid,
the whole normalized dim in the lane dimension of one VMEM block, and
``dgamma`` accumulated across grid steps relying on the TPU grid's
sequential execution order.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .layer_norm import _block_rows, _round_up

_f32 = jnp.float32


def _fwd_kernel(x_ref, *refs, eps, affine):
    if affine:
        w_ref, y_ref, rstd_ref = refs
    else:
        y_ref, rstd_ref = refs
    x = x_ref[...].astype(_f32)
    ms = jnp.mean(x * x, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y = x * rstd
    if affine:
        y = y * w_ref[...].astype(_f32)
    y_ref[...] = y.astype(y_ref.dtype)
    rstd_ref[...] = rstd


def _bwd_kernel(g_ref, x_ref, rstd_ref, *refs, affine):
    if affine:
        w_ref, dx_ref, dw_ref = refs
    else:
        (dx_ref,) = refs
    g = g_ref[...].astype(_f32)
    xhat = x_ref[...].astype(_f32) * rstd_ref[...]
    gh = g * w_ref[...].astype(_f32) if affine else g
    # d/dx of x * rsqrt(mean(x^2)+eps): the mean(gh*xhat) term is the
    # rstd-derivative contribution (no mean-centering term, unlike LN)
    c2 = jnp.mean(gh * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((gh - xhat * c2) * rstd_ref[...]).astype(dx_ref.dtype)
    if affine:
        @pl.when(pl.program_id(0) == 0)
        def _init():
            dw_ref[...] = jnp.zeros_like(dw_ref)
        dw_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)


def rms_forward(x2d, weight, eps, interpret=False):
    """x2d (rows, N); weight (N,) or None. → (y, rstd), rstd fp32 with
    shape (rows, 1)."""
    rows, n = x2d.shape
    affine = weight is not None
    bm = _block_rows(rows, n)
    rows_p = _round_up(rows, bm)
    if rows_p != rows:
        x2d = jnp.pad(x2d, ((0, rows_p - rows), (0, 0)))
    row_spec = pl.BlockSpec((bm, n), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((bm, 1), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, n), lambda i: (0, 0))
    args = [x2d]
    in_specs = [row_spec]
    if affine:
        args.append(weight.reshape(1, n))
        in_specs.append(vec_spec)
    y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, affine=affine),
        grid=(rows_p // bm,),
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, n), x2d.dtype),
            jax.ShapeDtypeStruct((rows_p, 1), _f32),
        ],
        interpret=interpret,
    )(*args)
    return y[:rows], rstd[:rows]


def rms_backward(g2d, x2d, rstd, weight, interpret=False):
    """→ dx (and, when affine, dgamma in fp32, shape (N,))."""
    rows, n = x2d.shape
    affine = weight is not None
    bm = _block_rows(rows, n)
    rows_p = _round_up(rows, bm)
    if rows_p != rows:
        # zero-padded g rows contribute nothing to dgamma
        g2d = jnp.pad(g2d, ((0, rows_p - rows), (0, 0)))
        x2d = jnp.pad(x2d, ((0, rows_p - rows), (0, 0)))
        rstd = jnp.pad(rstd, ((0, rows_p - rows), (0, 0)),
                       constant_values=1.0)
    row_spec = pl.BlockSpec((bm, n), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((bm, 1), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, n), lambda i: (0, 0))
    args = [g2d, x2d, rstd]
    in_specs = [row_spec, row_spec, stat_spec]
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows_p, n), x2d.dtype)]
    if affine:
        args.append(weight.reshape(1, n))
        in_specs.append(vec_spec)
        out_specs.append(vec_spec)
        out_shape.append(jax.ShapeDtypeStruct((1, n), _f32))
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, affine=affine),
        grid=(rows_p // bm,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    if affine:
        dx, dw = outs
        return dx[:rows], dw.reshape(n)
    return (outs[0][:rows],)
