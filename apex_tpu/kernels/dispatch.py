"""Kernel dispatch policy: backend selection + the measured-threshold
tier decision every Pallas kernel routes through.

Two layers live here:

* **backend mode** (:func:`pallas_mode` / :func:`force_mode`) — moved
  from ``ops/pallas/__init__.py``: 'compiled' on TPU, 'interpret' for
  CPU kernel testing, ``None`` for the pure-jnp fallback.  Dispatch
  happens at trace time; already-jitted callables keep the mode they
  traced with.

* **tier policy** (:func:`register_kernel` / :func:`decide` /
  :func:`run`) — the round-5 lesson turned into machinery.  Three
  kernel candidates were gated off as frozen constants (norms -> XLA
  default, flash only >= 512 keys, lm_head_xent 0.69x); this module
  makes the gate *data*: every kernel registers with a declared XLA
  fallback and a threshold probe (the KERNEL-FALLBACK lint rule
  enforces both), :func:`decide` consults the calibration ledger
  (:mod:`apex_tpu.kernels.ledger`) at trace time — a static, hashable
  decision, no host sync inside jit — and falls back to XLA below the
  kernel's measured win region.  Every decision is emitted once as a
  ``kernels.dispatch`` observe event carrying the ledger entry that
  made it, so dispatch is auditable from the event log alone.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from typing import Callable, Optional

import jax

from . import ledger as _ledger

_forced = [None]


def pallas_mode():
    """Returns 'compiled' | 'interpret' | None (use the jnp fallback).

    Priority: force_mode() context > APEX_TPU_PALLAS env var
    ('off'/'0', 'interpret', 'compiled') > backend autodetect.
    """
    if _forced[0] is not None:
        return None if _forced[0] == "off" else _forced[0]
    env = os.environ.get("APEX_TPU_PALLAS", "").lower()
    if env in ("0", "off"):
        return None
    if env in ("interpret", "compiled"):
        return env
    return "compiled" if jax.default_backend() == "tpu" else None


@contextlib.contextmanager
def force_mode(mode):
    """Force kernel dispatch for a scope: 'compiled', 'interpret' or 'off'.

    Note: dispatch happens at trace time, so already-jitted callables keep
    the mode they were traced with.
    """
    prev = _forced[0]
    _forced[0] = mode
    try:
        yield
    finally:
        _forced[0] = prev


# The masked-vocabulary convention, in one place: logits at MASKED_FILL
# (-1e30) mean "this column does not exist" (lane-padded heads'
# pad columns, nucleus-filtered tokens); consumers treat anything at or
# below MASKED_LOGIT_THR (-1e29) as masked — softmax contributions
# underflow to 0 there, and the smoothing-aware losses
# (nn.functional.cross_entropy, contrib.xentropy) exclude such columns
# from the label-smoothing term and its divisor.
MASKED_FILL = -1e30
MASKED_LOGIT_THR = -1e29


# Round-5 norm-kernel verdict (BENCH_HISTORY round 5).  The
# variance-controlled isolated A/B (median of 5 interleaved reps)
# put every LN/RMS row in a 0.93-1.03x band around XLA's own fusion —
# the round-3 "1.73x LN win" was single-run noise — and the IN-STEP
# A/B then showed routing norms to XLA is a real headline win:
# BERT 1178->1252 (+6.3%), GPT 1044->1067 (+2.2%), Llama 1396->1469
# (+5.2%) seq/s.  A Pallas custom call is a fusion barrier; XLA fuses
# the norm into its producers/consumers when allowed to own it.
# Default therefore defers to XLA on compiled TPU; the kernels stay
# for interpret-mode parity coverage and APEX_TPU_NORM_KERNEL=1 opts
# back in on-chip.
_NORM_KERNEL_DEFAULT_ON = False


def norm_kernel_mode():
    """Effective dispatch mode for the LayerNorm/RMSNorm Pallas
    kernels: ``pallas_mode()`` gated by APEX_TPU_NORM_KERNEL
    ('auto'/'1'/'0') on compiled backends.  A ``force_mode`` scope
    overrides the gate (parity checks and tests force the kernel arm
    explicitly and must never silently self-compare); interpret mode
    always exercises the kernels — that mode exists to test them."""
    if _forced[0] is not None:
        return pallas_mode()
    mode = pallas_mode()
    if mode != "compiled":
        return mode
    env = os.environ.get("APEX_TPU_NORM_KERNEL", "auto").lower()
    if env in ("1", "on"):
        return mode
    if env in ("0", "off"):
        return None
    return mode if _NORM_KERNEL_DEFAULT_ON else None


# ---------------------------------------------------------------------------
# Shape fingerprints — the ledger key half the chip doesn't supply
# ---------------------------------------------------------------------------


def shape_fp(**dims) -> str:
    """Canonical fingerprint: sorted ``k=v`` pairs joined by ','.

    The SAME helper builds the key at probe time (bench), decision time
    (dispatch) and pricing time (planner) — matching by construction."""
    return ",".join(f"{k}={dims[k]}" for k in sorted(dims))


def parse_fp(fp: str) -> dict:
    """Inverse of :func:`shape_fp`; int-valued where possible."""
    out = {}
    for part in str(fp).split(","):
        k, _, v = part.partition("=")
        if not k:
            continue
        try:
            out[k] = int(v)
        except ValueError:
            out[k] = v
    return out


def attention_fp(b, h, sq, sk, d, dtype="float32", causal=False) -> str:
    return shape_fp(b=int(b), h=int(h), sq=int(sq), sk=int(sk), d=int(d),
                    dtype=str(dtype), causal=int(bool(causal)))


def multi_tensor_fp(op: str, n_elements: int, n_tensors: int,
                    dtype="float32") -> str:
    return shape_fp(op=str(op), n=int(n_elements), t=int(n_tensors),
                    dtype=str(dtype))


def vocab_chain_fp(n, v, e, dtype="float32") -> str:
    return shape_fp(n=int(n), v=int(v), e=int(e), dtype=str(dtype))


# ---------------------------------------------------------------------------
# Kernel registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One registered kernel: its declared XLA fallback and the default
    threshold probe that decides dispatch when the ledger has no
    measurement for the shape (the probe encodes the frozen round-5
    receipts; the ledger overrides it with live data)."""
    name: str
    xla_fallback: str            # where the XLA path lives (dotted path)
    threshold_probe: Callable    # (dims: dict) -> (threshold, use_pallas)
    doc: str = ""
    # () -> [(tier, fn, example_avals)] — the jaxpr verifier
    # (lint.jaxpr_audit) traces both tiers abstractly through this
    audit_programs: Optional[Callable] = None


KERNELS: dict = {}


def register_kernel(name: str, *, xla_fallback: str,
                    threshold_probe: Callable, doc: str = "",
                    audit_programs: Optional[Callable] = None) -> KernelSpec:
    """Register a kernel with the dispatch policy.  Both ``xla_fallback``
    and ``threshold_probe`` are mandatory by construction — the
    KERNEL-FALLBACK lint rule flags registrations without them.
    ``audit_programs`` makes both tiers traceable by the jaxpr
    verifier: a zero-arg callable yielding ``(tier, fn, example)``
    triples with abstract (ShapeDtypeStruct) examples."""
    if not xla_fallback or threshold_probe is None:
        raise ValueError(
            f"kernel {name!r} must declare an XLA fallback and a "
            f"threshold probe (KERNEL-FALLBACK)")
    spec = KernelSpec(name=name, xla_fallback=xla_fallback,
                      threshold_probe=threshold_probe, doc=doc,
                      audit_programs=audit_programs)
    KERNELS[name] = spec
    return spec


def catalog() -> dict:
    """Snapshot of the registered kernels (name -> KernelSpec)."""
    return dict(KERNELS)


# ---------------------------------------------------------------------------
# The tier decision
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Decision:
    """One dispatch decision — hashable and static (safe inside jit
    tracing; nothing here touches device values)."""
    kernel: str
    tier: str                    # "pallas" | "xla"
    shape_fp: str
    chip: str
    source: str                  # "ledger" | "probe" | "mode"
    threshold: Optional[float] = None
    win: Optional[float] = None


_decisions_lock = threading.Lock()
_decisions: dict = {}            # (kernel, fp, mode, chip) -> Decision


def decide(name: str, fp: str) -> Decision:
    """Pick the tier for ``(kernel, shape)`` at trace time.

    Policy, in order: no Pallas backend -> XLA; a ledger entry for
    ``(chip, kernel, fp)`` -> its measured verdict (win >= 1 runs the
    kernel, win < 1 falls back — in interpret mode too, so the policy
    itself is testable on CPU); otherwise the kernel's registered
    threshold probe (interpret mode with no entry defaults to the
    kernel — that mode exists to exercise it).  The first decision per
    key emits a ``kernels.dispatch`` observe event carrying the ledger
    entry that made it.
    """
    mode = pallas_mode()
    chip = _ledger.chip_name()
    key = (name, fp, mode, chip)
    with _decisions_lock:
        hit = _decisions.get(key)
    if hit is not None:
        return hit

    entry = None
    if mode is None:
        d = Decision(name, "xla", fp, chip, "mode")
    else:
        entry = _ledger.get_ledger().lookup_kernel(chip, name, fp)
        if entry is not None:
            tier = "pallas" if entry["win"] >= 1.0 else "xla"
            d = Decision(name, tier, fp, chip, "ledger",
                         threshold=entry.get("threshold"),
                         win=entry["win"])
        else:
            spec = KERNELS.get(name)
            if spec is None:
                d = Decision(name, "pallas", fp, chip, "mode")
            elif mode == "interpret":
                d = Decision(name, "pallas", fp, chip, "mode")
            else:
                threshold, use_pallas = spec.threshold_probe(parse_fp(fp))
                d = Decision(name, "pallas" if use_pallas else "xla", fp,
                             chip, "probe", threshold=threshold)

    with _decisions_lock:
        first = key not in _decisions
        _decisions[key] = d
    if first:
        from ..observe import registry as _obs
        # tpu-lint: disable=OBS-IN-JIT deliberate trace-time telemetry:
        # decide() runs while tracing and the dispatch event must fire
        # exactly ONCE per new (kernel, shape, mode, chip) decision —
        # once-at-trace-time is the contract here, not dead telemetry
        _obs.event("kernels.dispatch", kernel=d.kernel, tier=d.tier,
                   shape_fp=d.shape_fp, chip=d.chip, source=d.source,
                   threshold=d.threshold, win=d.win,
                   ledger_entry=entry)
        # tpu-lint: disable=OBS-IN-JIT same contract as the event above:
        # the per-tier counter increments once per new decision
        _obs.counter(f"kernels.dispatch.{d.kernel}.{d.tier}").inc()
    return d


def decisions() -> list:
    """Snapshot of every decision taken so far (bench headline stages
    attach this to their records so throughput is attributable per
    kernel tier)."""
    with _decisions_lock:
        return [dataclasses.asdict(d) for d in _decisions.values()]


def reset_decisions() -> None:
    """Forget cached decisions (tests; also required after the ledger
    is re-pointed — decisions embed ledger verdicts)."""
    with _decisions_lock:
        _decisions.clear()


def measured_threshold(name: str, dim: str, default: int) -> int:
    """A measured dispatch threshold for ``kernel`` along fingerprint
    dimension ``dim``: the smallest probed value of ``dim`` whose entry
    wins (xla_us/pallas_us >= 1).  Falls back to ``default`` when the
    ledger has no winning entry for this chip — the frozen prior keeps
    deciding until someone measures."""
    entries = _ledger.get_ledger().kernel_entries(_ledger.chip_name(), name)
    winners = []
    for fp, rec in entries.items():
        win = rec.get("win")
        val = parse_fp(fp).get(dim)
        if isinstance(val, int) and isinstance(win, (int, float)) \
                and win >= 1.0:
            winners.append(val)
    return min(winners) if winners else default


# ---------------------------------------------------------------------------
# Executor-dispatched kernel programs (the eager tier surface)
# ---------------------------------------------------------------------------


def run(name: str, fp: str, args, *, pallas_fn: Callable,
        xla_fn: Callable, static_key=(), donate_argnums=()):
    """Dispatch one kernel call as an executor Program whose KIND names
    the tier — ``kernel.<name>.<tier>`` — so
    ``step_cache.kind_stats("kernel.flash_attention.xla")`` pins which
    path a shape actually took (the dispatch-policy acceptance test).

    Donation-safe: ``donate_argnums`` is resolved through the one
    :class:`~apex_tpu.runtime.executor.DonationPolicy` and the resolved
    flag joins the static key, exactly like the optimizer-step programs.
    """
    from ..runtime import executor as _executor

    d = decide(name, fp)
    fn = pallas_fn if d.tier == "pallas" else xla_fn
    donate = _executor.donation.enabled and bool(donate_argnums)

    def kernel_run(*a):
        return fn(*a)

    prog = _executor.Program(
        f"kernel.{name}.{d.tier}", (static_key, fp, donate), kernel_run,
        donate_argnums=tuple(donate_argnums) if donate else ())
    return _executor.executor.submit(prog, tuple(args))
