"""apex_tpu.kernels — the measured Pallas kernel tier.

The reference Apex ships its L0 layer as CUDA extensions (``csrc/``:
fused optimizers, layer norm, attention, xentropy) that are simply
always on.  This package is the TPU rebuild's answer with the round-4/5
lesson baked in: a kernel is a *claim* that must be measured, so every
kernel here registers with :mod:`.dispatch` carrying a declared XLA
fallback and a threshold probe, dispatch consults the on-disk
calibration ledger (:mod:`.ledger`, keyed by chip + shape fingerprint)
at trace time, and anything below its measured win region runs XLA.
``docs/kernels.md`` is the catalog, including the negative results.

Import order matters: ``.dispatch`` first (the registry the kernel
modules register into), then the kernel modules, so partially-imported
cycles through ``ops.pallas`` compat imports always find the dispatch
surface already bound.
"""
from __future__ import annotations

from . import ledger  # noqa: F401
from . import dispatch  # noqa: F401
from .dispatch import (  # noqa: F401
    MASKED_FILL,
    MASKED_LOGIT_THR,
    Decision,
    KernelSpec,
    attention_fp,
    catalog,
    decide,
    decisions,
    force_mode,
    measured_threshold,
    multi_tensor_fp,
    norm_kernel_mode,
    pallas_mode,
    parse_fp,
    register_kernel,
    reset_decisions,
    run,
    shape_fp,
    vocab_chain_fp,
)
from .ledger import (  # noqa: F401
    Ledger,
    chip_name,
    get_ledger,
    set_path as set_ledger_path,
)

# kernel modules (each registers itself with dispatch on import)
from . import attention  # noqa: F401
from . import layer_norm  # noqa: F401
from . import rms_norm  # noqa: F401
from . import xentropy  # noqa: F401
from . import lm_head_xent  # noqa: F401
from . import multi_tensor  # noqa: F401
from . import vocab_chain  # noqa: F401
from . import spec_verify  # noqa: F401

from .multi_tensor import (  # noqa: F401
    fused_adam,
    fused_sgd,
    multi_tensor_adam,
    multi_tensor_sgd,
)
from .vocab_chain import vocab_chain_loss  # noqa: F401

__all__ = [
    "MASKED_FILL",
    "MASKED_LOGIT_THR",
    "Decision",
    "KernelSpec",
    "Ledger",
    "attention_fp",
    "catalog",
    "chip_name",
    "decide",
    "decisions",
    "force_mode",
    "fused_adam",
    "fused_sgd",
    "get_ledger",
    "measured_threshold",
    "multi_tensor_adam",
    "multi_tensor_fp",
    "multi_tensor_sgd",
    "norm_kernel_mode",
    "pallas_mode",
    "parse_fp",
    "register_kernel",
    "reset_decisions",
    "run",
    "set_ledger_path",
    "shape_fp",
    "vocab_chain_fp",
    "vocab_chain_loss",
]
