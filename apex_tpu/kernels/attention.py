"""Pallas TPU fused attention kernels.

TPU re-design of the reference's ``fast_*_multihead_attn`` extensions
(apex/contrib/csrc/multihead_attn/, ~5900 LoC of fused QKV GEMM +
strided-batched attention GEMMs + fused mask/softmax).  The reference kernel
materializes the full (Sq, Sk) softmax; the modern TPU analogue is a
flash-attention kernel — blockwise online softmax, O(S) memory, saving only
the per-row logsumexp for the backward (SURVEY.md §2.2 maps
fast_multihead_attn → "Pallas fused attention, flash-style").

Layout: q (B, H, Sq, D), k/v (B, H, Sk, D), flattened to (B·H, S, D) for the
kernels.  Grid (batch·head, q-blocks, k-blocks) with the k dimension
innermost: TPU grids execute sequentially, so the running max / denominator /
accumulator live in VMEM scratch across the k sweep (the canonical TPU flash
pattern).  The backward recomputes attention blockwise from the saved
logsumexp: one kernel accumulates dq over the k sweep, a second accumulates
dk/dv over the q sweep.  All softmax/accumulation math in fp32.

An additive ``bias`` (broadcastable (B|1, Sq|1, Sk)) carries both mask
flavors of the reference API (key_padding_mask → 0/-inf per key,
attn_mask → additive (Sq, Sk)); ``causal`` applies the in-kernel triangular
mask the reference calls ``mask_future_timesteps``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_f32 = jnp.float32
_NEG = -1e30  # finite "-inf": keeps exp(s - m) well-defined in masked blocks


def _ceil_div(a, b):
    return (a + b - 1) // b


_VMEM_BUDGET = 10 * 1024 * 1024  # conservative slice of the ~16 MiB/core VMEM


def _vmem_estimate(bq, bk, d):
    """Worst-case fp32 bytes resident per grid step across the three kernels
    (input blocks + (bq, bk) score intermediates + scratch accumulators)."""
    f = 4
    fwd = (2 * bq * d + 2 * bk * d) * f + 3 * bq * bk * f + 2 * bq * f
    dkv = (3 * bq * d + 2 * bk * d) * f + 4 * bq * bk * f + 2 * bk * d * f
    return max(fwd, dkv)


def _block_sizes(sq, sk, d):
    bq = min(256, _round8(sq))
    bk = min(512, _round8(sk))
    # shrink blocks until the per-step working set fits the VMEM budget
    # (large head dims would otherwise OOM VMEM at the default 256/512)
    while _vmem_estimate(bq, bk, d) > _VMEM_BUDGET and bk > 128:
        bk //= 2
    while _vmem_estimate(bq, bk, d) > _VMEM_BUDGET and bq > 128:
        bq //= 2
    return bq, bk


def vmem_fit(sq, sk, d):
    """VMEM-fit report for the chosen block sizes (bench --kernels guard)."""
    bq, bk = _block_sizes(sq, sk, d)
    est = _vmem_estimate(bq, bk, d)
    return {"bq": bq, "bk": bk, "est_bytes": est,
            "budget_bytes": _VMEM_BUDGET, "fits": est <= _VMEM_BUDGET}


def _round8(x):
    return max(8, (x + 7) // 8 * 8)


def _hash_keep_u32(rows, cols, bh, seed):
    """Counter-based per-element hash (murmur3-finalizer style) of
    (seed, batch·head, global row, global col) → uint32.  Pure uint32
    vector arithmetic: lowers on Mosaic AND in interpret mode, and the
    jnp oracle (``dropout_keep_reference``) reproduces it bit-exactly —
    unlike the hardware PRNG, which interpret mode mocks as zeros.  The
    mask is a function of absolute positions only, so forward and both
    backward kernels regenerate it identically regardless of block
    sizes.  This is the TPU analogue of the reference's fused-dropout
    Philox replay (apex/contrib/csrc/multihead_attn/dropout.cuh:
    curand_uniform4 regenerated from the saved seed/offset in bwd)."""
    h = (rows.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + cols.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
         + seed.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
         + bh.astype(jnp.uint32) * jnp.uint32(0x27D4EB2F))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return h


def _seed_vec(seed, row_off, col_off):
    """(3,) int32 SMEM payload [seed, row_off, col_off] for the kernels
    (offsets may be traced scalars — ring chunks compute them per hop)."""
    return jnp.stack([jnp.asarray(seed, jnp.int32).reshape(()),
                      jnp.asarray(row_off, jnp.int32).reshape(()),
                      jnp.asarray(col_off, jnp.int32).reshape(())])


def _mult_from_hash(h, rate):
    """hash → inverted-dropout multiplier: 1/(1-rate) where the hash
    clears the keep threshold, 0 elsewhere.  THE single definition of
    the threshold/scaling — the kernels and the jnp oracle both call it,
    so their bit-exact agreement cannot drift."""
    thresh = jnp.uint32(min(int((1.0 - rate) * 2.0 ** 32), 2 ** 32 - 1))
    return jnp.where(h < thresh, jnp.float32(1.0 / (1.0 - rate)),
                     jnp.float32(0.0))


def _dropout_mult(i, j, b, bq, bk, seed_ref, rate):
    """(bq, bk) f32 multiplier grid: 1/(1-rate) on kept positions, 0 on
    dropped — inverted-dropout scaling applied to the attention probs.
    ``seed_ref`` is the (3,) SMEM vector [seed, row_off, col_off]: the
    offsets shift block coordinates to GLOBAL positions, so a chunked
    caller (ring attention) whose q/k blocks sit at arbitrary global
    offsets draws the exact mask the single-device kernel would."""
    rows = seed_ref[1] + i * bq \
        + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = seed_ref[2] + j * bk \
        + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return _mult_from_hash(
        _hash_keep_u32(rows, cols, jnp.asarray(b), seed_ref[0]), rate)


def dropout_keep_reference(b, sq, sk, seed, rate, row_off=0,
                           col_off=0):
    """jnp oracle of the in-kernel mask: (B·H, Sq, Sk) f32 multipliers,
    bit-identical to what the kernels generate (tests + fallback path).
    ``row_off``/``col_off`` shift to global coordinates for chunked
    callers (ring attention's jnp arm)."""
    rows = row_off + jax.lax.broadcasted_iota(jnp.int32, (b, sq, sk), 1)
    cols = col_off + jax.lax.broadcasted_iota(jnp.int32, (b, sq, sk), 2)
    bh = jax.lax.broadcasted_iota(jnp.int32, (b, sq, sk), 0)
    return _mult_from_hash(
        _hash_keep_u32(rows, cols, bh, jnp.asarray(seed)), rate)


def _mask_block(s, i, j, bq, bk, causal, window=None):
    """Causal (``rows >= cols``) and, with ``window``, Mistral-banded
    (``cols > rows - window``) masking of one score block."""
    if not causal:
        return s
    rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    keep = rows >= cols
    if window is not None:
        keep = jnp.logical_and(keep, cols > rows - window)
    return jnp.where(keep, s, _NEG)


def _block_has_unmasked(i, j, bq, bk, window=None):
    """Block-granular mirror of ``_mask_block``: true iff q-block ``i``
    x k-block ``j`` holds at least one unmasked entry — above-diagonal
    blocks fail the causal edge (max row >= min col), and with
    ``window`` blocks entirely BELOW the band fail the band edge
    (max col > min row - window).  The kernels skip compute on
    fully-masked blocks — banded attention therefore costs
    O(S·window), not O(S²).  This predicate and ``_mask_block`` must
    stay in lockstep if the mask convention ever changes."""
    ok = j * bk <= i * bq + bq - 1
    if window is not None:
        ok = jnp.logical_and(ok, j * bk + bk - 1 > i * bq - window)
    return ok


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, bq, bk, nk,
                has_bias, window=None, dropout_p=0.0):
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    if has_bias:
        bias_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    b, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(_f32)
        k = k_ref[0].astype(_f32)
        v = v_ref[0].astype(_f32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_f32) * scale
        if has_bias:
            s = s + bias_ref[0].astype(_f32)
        s = _mask_block(s, i, j, bq, bk, causal, window)

        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # dropout multiplies the (unnormalized) probs in the ACCUMULATOR
        # only; l keeps the full softmax sum, so out = dropout(P) @ v
        # exactly (P the normalized probs), matching the eager path
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            p = p * _dropout_mult(i, j, b, bq, bk, seed_ref, dropout_p)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot(
            p, v, preferred_element_type=_f32)
        m_scr[...] = m_new

    if causal:
        # skip k-blocks strictly above the diagonal: every entry is
        # masked, so the block's contribution is exactly p = 0 — the
        # update is an arithmetic no-op and the two MXU matmuls are
        # pure waste (~half the blocks as Sq grows; the reason causal
        # flash exists).  Numerics are bit-identical to the unskipped
        # sweep.
        pl.when(_block_has_unmasked(i, j, bq, bk, window))(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _fin():
        l = l_scr[...]
        # defensive only: with finite -1e30 masking l >= 1 always, so a
        # fully-masked row yields a uniform average over v (identical to
        # the jnp fallback path), not zeros
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(safe_l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
               scale, causal, bq, bk, nk, has_bias, window=None,
               dropout_p=0.0):
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    if has_bias:
        bias_ref, dq_ref, acc_scr = refs
    else:
        dq_ref, acc_scr = refs
    b, i, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(_f32)
        k = k_ref[0].astype(_f32)
        v = v_ref[0].astype(_f32)
        do = do_ref[0].astype(_f32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_f32) * scale
        if has_bias:
            s = s + bias_ref[0].astype(_f32)
        s = _mask_block(s, i, j, bq, bk, causal, window)
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=_f32)
        if dropout_p > 0.0:
            # d(out)/d(P) routes through the dropout multiplier; delta
            # already includes it (delta = sum(do*out), out dropped)
            dp = dp * _dropout_mult(i, j, b, bq, bk, seed_ref, dropout_p)
        ds = p * (dp - delta_ref[0])
        acc_scr[...] += jax.lax.dot(ds, k, preferred_element_type=_f32)

    if causal:
        # fully-masked block: p = 0 → ds = 0, contributes nothing to dq
        pl.when(_block_has_unmasked(i, j, bq, bk, window))(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _fin():
        dq_ref[0] = (acc_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                scale, causal, bq, bk, nq, has_bias, window=None,
                dropout_p=0.0):
    refs = list(refs)
    seed_ref = refs.pop(0) if dropout_p > 0.0 else None
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = refs
    # grid is (bh, k-blocks, q-blocks): q innermost for the accumulation
    b = pl.program_id(0)
    j, i = pl.program_id(1), pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0].astype(_f32)
        k = k_ref[0].astype(_f32)
        v = v_ref[0].astype(_f32)
        do = do_ref[0].astype(_f32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=_f32) * scale
        if has_bias:
            s = s + bias_ref[0].astype(_f32)
        s = _mask_block(s, i, j, bq, bk, causal, window)
        p = jnp.exp(s - lse_ref[0])  # (bq, bk)
        if dropout_p > 0.0:
            dmult = _dropout_mult(i, j, b, bq, bk, seed_ref, dropout_p)
            pd = p * dmult  # dropped probs: dv sees dropout(P)
        else:
            pd = p
        dv_scr[...] += jax.lax.dot_general(pd, do, (((0,), (0,)), ((), ())),
                                           preferred_element_type=_f32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=_f32)
        if dropout_p > 0.0:
            dp = dp * dmult
        ds = p * (dp - delta_ref[0])  # (bq, bk)
        dk_scr[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                           preferred_element_type=_f32)

    if causal:
        # q-block entirely above the diagonal contributes nothing to
        # this k-block's dk/dv (every score masked, p = 0) — skip the
        # four matmuls
        pl.when(_block_has_unmasked(i, j, bq, bk, window))(_compute)
    else:
        _compute()

    @pl.when(i == nq - 1)
    def _fin():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bias_spec(bias, bq, bk, for_dkv=False):
    b_, sq_, _ = bias.shape
    if for_dkv:
        def idx(b, j, i):
            return (b if b_ > 1 else 0, i if sq_ > 1 else 0, j)
    else:
        def idx(b, i, j):
            return (b if b_ > 1 else 0, i if sq_ > 1 else 0, j)
    return pl.BlockSpec((1, bq if sq_ > 1 else 1, bk), idx)


def flash_attention_fwd(q3, k3, v3, bias, scale, causal, interpret=False,
                        window=None, dropout_p=0.0, dropout_seed=None,
                        dropout_row_off=0, dropout_col_off=0):
    """q3 (BH, Sq, D), k3/v3 (BH, Sk, D), bias (B|1, Sq|1, Sk) or None.
    ``dropout_p`` > 0 applies in-kernel inverted dropout to the attention
    probs, regenerated from ``dropout_seed`` (int32 scalar) in the
    backward.  Returns (out (BH, Sq, D), lse (BH, Sq) fp32)."""
    if dropout_p and not 0.0 <= dropout_p < 1.0:
        raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    bq, bk = _block_sizes(sq, sk, d)
    sq_p, sk_p = _ceil_div(sq, bq) * bq, _ceil_div(sk, bk) * bk
    q3 = jnp.pad(q3, ((0, 0), (0, sq_p - sq), (0, 0)))
    k3 = jnp.pad(k3, ((0, 0), (0, sk_p - sk), (0, 0)))
    v3 = jnp.pad(v3, ((0, 0), (0, sk_p - sk), (0, 0)))
    has_bias = bias is not None
    if not has_bias and sk_p != sk:
        # mask the padded keys so they don't leak into the softmax
        bias = jnp.zeros((1, 1, sk), _f32)
        has_bias = True
    if has_bias:
        bias = jnp.pad(bias.astype(_f32),
                       ((0, 0), (0, sq_p - bias.shape[1] if
                                 bias.shape[1] > 1 else 0),
                        (0, sk_p - bias.shape[2])),
                       constant_values=_NEG)
    nq, nk = sq_p // bq, sk_p // bk
    grid = (bh, nq, nk)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [q3, k3, v3]
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(_seed_vec(dropout_seed, dropout_row_off,
                              dropout_col_off))
    if has_bias:
        in_specs.append(_bias_spec(bias, bq, bk))
        args.append(bias)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, nk=nk, has_bias=has_bias,
                          window=window, dropout_p=dropout_p),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_p, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, sq_p, 1), _f32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), _f32),
            pltpu.VMEM((bq, 1), _f32),
            pltpu.VMEM((bq, d), _f32),
        ],
        interpret=interpret,
    )(*args)
    return out[:, :sq], lse[:, :sq, 0]


def flash_attention_bwd(q3, k3, v3, bias, out, lse, g, scale, causal,
                        interpret=False, window=None, dropout_p=0.0,
                        dropout_seed=None, dropout_row_off=0,
                        dropout_col_off=0):
    """→ (dq, dk, dv) with the shapes/dtypes of q3/k3/v3."""
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    bq, bk = _block_sizes(sq, sk, d)
    sq_p, sk_p = _ceil_div(sq, bq) * bq, _ceil_div(sk, bk) * bk
    delta = jnp.sum(g.astype(_f32) * out.astype(_f32), axis=-1)  # (BH, Sq)
    q3 = jnp.pad(q3, ((0, 0), (0, sq_p - sq), (0, 0)))
    k3 = jnp.pad(k3, ((0, 0), (0, sk_p - sk), (0, 0)))
    v3 = jnp.pad(v3, ((0, 0), (0, sk_p - sk), (0, 0)))
    g = jnp.pad(g, ((0, 0), (0, sq_p - sq), (0, 0)))
    # padded q rows: lse=0 → p=exp(s-0); keep them harmless with lse=+big
    lse = jnp.pad(lse, ((0, 0), (0, sq_p - sq)),
                  constant_values=-_NEG)[..., None]
    delta = jnp.pad(delta, ((0, 0), (0, sq_p - sq)))[..., None]
    has_bias = bias is not None
    if not has_bias and sk_p != sk:
        bias = jnp.zeros((1, 1, sk), _f32)
        has_bias = True
    if has_bias:
        bias = jnp.pad(bias.astype(_f32),
                       ((0, 0), (0, sq_p - bias.shape[1] if
                                 bias.shape[1] > 1 else 0),
                        (0, sk_p - bias.shape[2])),
                       constant_values=_NEG)
    nq, nk = sq_p // bq, sk_p // bk

    common = [q3, k3, v3, g]
    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    lse_spec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))

    in_specs = [q_spec, k_spec, k_spec, q_spec, lse_spec, lse_spec]
    args = common + [lse, delta]
    seed_arr = (_seed_vec(dropout_seed, dropout_row_off,
                          dropout_col_off)
                if dropout_p > 0.0 else None)
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed_arr)
    if has_bias:
        in_specs.append(_bias_spec(bias, bq, bk))
        args.append(bias)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, nk=nk, has_bias=has_bias,
                          window=window, dropout_p=dropout_p),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), _f32)],
        interpret=interpret,
    )(*args)

    # dk/dv: swap loop order — k blocks in the middle, q innermost
    q_spec2 = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    k_spec2 = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    lse_spec2 = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0))
    in_specs2 = [q_spec2, k_spec2, k_spec2, q_spec2, lse_spec2, lse_spec2]
    args2 = common + [lse, delta]
    if dropout_p > 0.0:
        in_specs2.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args2.append(seed_arr)
    if has_bias:
        in_specs2.append(_bias_spec(bias, bq, bk, for_dkv=True))
        args2.append(bias)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, nq=nq, has_bias=has_bias,
                          window=window, dropout_p=dropout_p),
        grid=(bh, nk, nq),
        in_specs=in_specs2,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_p, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, sk_p, d), v3.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), _f32)] * 2,
        interpret=interpret,
    )(*args2)
    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


# ---------------------------------------------------------------------------
# Dispatch registration
# ---------------------------------------------------------------------------

# the XLA fallback's score tensor (fwd scores + softmax residual for
# backward, f32) must also stay SMALL in absolute terms — key length
# alone ignores the B*H factor.  128 MB keeps the fallback's footprint
# noise-level next to activations; beyond it flash's O(S) memory is the
# point even where it is a little slower per-FLOP.
XLA_SCORES_BYTE_CAP = 128 * 1024 * 1024


def flash_min_sk() -> int:
    """Key-length threshold below which compiled dispatch prefers XLA's
    own attention over the flash kernel.

    Measured on v5e (bench --kernels-timing, fwd+bwd).  Round 3, before
    causal block skipping: S=256 ran 0.82x XLA.  Round 4, with skipping
    (BENCH_HISTORY round-4 A/B table): S=256 1.06x, S=512 0.96x (both
    noise-level), S=1024 causal 1.24x, S=2048/D=128 1.19x, banded
    S=2048/w=256 1.82x — flash decisively wins the shapes it exists
    for, and the 256-512 boundary is a wash.  APEX_TPU_FLASH_MIN_SK
    overrides (0 forces flash everywhere); otherwise a ledger-measured
    win for this chip moves the boundary off the 512 prior."""
    import os
    env = os.environ.get("APEX_TPU_FLASH_MIN_SK")
    if env is not None:
        return int(env)
    from .dispatch import measured_threshold
    return measured_threshold("flash_attention", "sk", 512)


def _flash_probe(dims):
    # no-ledger default: the kernel from the measured min-sk boundary
    # up, and ALSO wherever the XLA fallback's score tensor would be
    # memory-harmful regardless of per-FLOP speed
    min_sk = flash_min_sk()
    sk = dims.get("sk", 0)
    scores = (dims.get("b", 1) * dims.get("h", 1) * dims.get("sq", 1)
              * sk * 4)
    return min_sk, sk >= min_sk or scores > XLA_SCORES_BYTE_CAP


def _audit_programs():
    """Both tiers on one abstract causal shape for the jaxpr verifier:
    the Pallas fwd (staged pallas_call, not executed) and the declared
    XLA reference."""
    sds = jax.ShapeDtypeStruct
    f32 = jnp.float32
    q3 = sds((2, 128, 64), f32)              # (B*H, S, D) kernel layout
    q4 = sds((1, 2, 128, 64), f32)           # (B, H, S, D) reference layout
    scale = 64 ** -0.5

    def _pallas(q, k, v):
        return flash_attention_fwd(q, k, v, None, scale, True)[0]

    def _xla(q, k, v):
        from ..contrib.multihead_attn.attn_funcs import attention_reference
        return attention_reference(q, k, v, None, True, scale)

    return [("pallas", _pallas, (q3, q3, q3)),
            ("xla", _xla, (q4, q4, q4))]


def _register():
    from .dispatch import register_kernel
    register_kernel(
        "flash_attention",
        xla_fallback=(
            "apex_tpu.contrib.multihead_attn.attn_funcs"
            ".attention_reference"),
        threshold_probe=_flash_probe,
        doc="Blockwise online-softmax attention (fwd + recompute bwd)",
        audit_programs=_audit_programs)


_register()
