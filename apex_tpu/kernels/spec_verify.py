"""spec_verify: the serve engine's batched draft/verify decode tick as
a measured dispatch tier.

Speculative decoding is a perf *claim* — "the draft accepts enough
tokens that one (k+1)-wide verify pass beats k+1 one-token decode
ticks" — so it registers here like any Pallas kernel and gets priced by
the same ledger machinery.  The tiers:

* **"pallas"** — the fused draft-propose + target-verify program body
  (:func:`apex_tpu.serve.kernels.build_spec_verify_fn`), committing
  1..k+1 tokens per dispatch;
* **"xla"** (the declared fallback) — the plain one-token decode
  program (:func:`apex_tpu.serve.kernels.build_decode_fn`).

Both tiers emit bitwise-identical greedy tokens (acceptance only ever
truncates to a prefix of the target's own argmax stream), so a ledger
entry's ``win`` is a pure tokens/s ratio at equal batch — measured by
``bench.py --kernels``' spec_verify probe, which times one verify
dispatch against the k+1 chained decode dispatches it replaces on a
self-draft (full-acceptance) trace.  ``ServeEngine(spec="auto")``
consults :func:`~apex_tpu.kernels.dispatch.decide` with this kernel's
fingerprint per packed bucket shape and falls back to plain decode
ticks below the win region; with no Pallas backend (CPU serving)
``decide`` says "xla" — tests and CPU benches opt in with
``spec="on"``.

This module deliberately imports nothing from ``apex_tpu.serve`` at
module level — it exists so the kernel is in :func:`catalog` whenever
``apex_tpu.kernels`` is, keeping the jaxpr verifier's "every registered
kernel, both tiers" sweep order-independent.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .dispatch import measured_threshold, register_kernel, shape_fp


def spec_verify_fp(*, b, k, s_t, s_d, dtype) -> str:
    """Ledger fingerprint for one spec-verify dispatch shape: batch
    bucket ``b``, draft depth ``k``, gathered target/draft linear cache
    widths ``s_t``/``s_d`` (table bucket x block_size), pool dtype.
    Built by the SAME helper at probe time (bench) and decision time
    (the engine's ``spec="auto"`` path)."""
    return shape_fp(b=int(b), k=int(k), s_t=int(s_t), s_d=int(s_d),
                    dtype=str(dtype))


def _spec_verify_probe(dims):
    """No-ledger prior: speculative verify pays when the draft proposes
    at least ``thr`` tokens per tick — at the >= 2 tokens/tick
    acceptance floor a k >= 2 draft amortizes the verify chunk's extra
    width.  A measured winning ``k`` boundary for this chip moves the
    threshold off the prior."""
    thr = float(measured_threshold("spec_verify", "k", 2))
    return thr, dims.get("k", 0) >= thr


def _audit_programs():
    """Both tiers traced abstractly: the fused verify body and the
    plain-decode fallback, over one tiny GPT pair (real modules — the
    bodies close over model structure; the OPERANDS stay abstract)."""
    from .. import nn as _nn
    from ..models.gpt import GptModel
    from ..serve.kernels import build_decode_fn, build_spec_verify_fn

    _nn.manual_seed(0)
    target = GptModel(vocab_size=31, hidden=16, layers=1, heads=2,
                      max_positions=32, dropout=0.0, attn_dropout=0.0)
    _nn.manual_seed(1)
    draft = GptModel(vocab_size=31, hidden=16, layers=1, heads=2,
                     max_positions=32, dropout=0.0, attn_dropout=0.0)
    target.eval()
    draft.eval()
    t_params = list(target.parameters()) + list(target.buffers())
    d_params = list(draft.parameters()) + list(draft.buffers())

    sds = jax.ShapeDtypeStruct
    i32 = jnp.int32
    bs, nblk, k, b, nb = 4, 6, 2, 2, 2
    t_vals = [sds(p.data.shape, p.data.dtype) for p in t_params]
    d_vals = [sds(p.data.shape, p.data.dtype) for p in d_params]
    pool = sds((1, 2, nblk, 2, bs, 8), jnp.float32)   # (L,2,NB,H,bs,D)
    toks = sds((b,), i32)
    pos = sds((b,), i32)
    tab = sds((b, nb), i32)

    spec_fn = build_spec_verify_fn(target, t_params, draft, d_params,
                                   bs, nblk, k)
    dec_fn = build_decode_fn(target, t_params, bs, nblk)
    return [("pallas", spec_fn,
             (t_vals, d_vals, pool, pool, toks, pos, tab, tab)),
            ("xla", dec_fn, (t_vals, pool, toks, pos, tab))]


register_kernel(
    "spec_verify",
    xla_fallback="apex_tpu.serve.kernels.build_decode_fn",
    threshold_probe=_spec_verify_probe,
    doc="Batched speculative draft/verify decode tick (serve v2): "
        "fused k-step draft propose + (k+1)-wide target verify vs the "
        "plain one-token decode program it replaces; both tiers emit "
        "bitwise-identical greedy tokens, so win is pure tokens/s",
    audit_programs=_audit_programs)
