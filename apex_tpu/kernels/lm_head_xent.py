"""EXPERIMENTAL: fused LM-head + cross-entropy Pallas kernel.

The round-4 GPT profile attributes ~43 ms of the 69.5 ms seq-128 step to
the vocab chain: the tied-head matmul materializes (N, V) logits (824 MB
bf16 at N=8192, V=50257), the loss re-reads them with f32 casts, and the
backward re-reads them again.  The standalone loss kernel
(ops/pallas/xentropy.py) measurably loses to XLA because its column
sweep is pure VPU work; THIS kernel amortizes the sweep inside the head
matmul — per (row-block, vocab-block) step the MXU computes the logits
block in VMEM and the online max/sum-exp/target/row-sum consume it in
register, so the full logits tensor never exists in HBM in either pass.

forward:  loss_i = lse_i - x_i·e_{y_i}   (plain CE; smoothing is out of
          scope for the prototype), residuals (x, emb, labels, lse)
backward: dlogits = gm·(exp(logit - lse) - onehot) is recomputed
          blockwise (flash-style), feeding dx = dlogits @ emb over a
          (rows, vocab) grid and demb = dlogitsᵀ @ x over the swapped
          grid — +1 recompute matmul per pass in exchange for ~3 GB of
          logits traffic per step.

Status: NOT wired into any model/loss path.  VERDICT (round-4 on-chip
A/B, BENCH_HISTORY): **0.69x** — the kernel LOSES to XLA's lowering of
the plain matmul + fused-xentropy chain at (8192, 50257, 768) fwd+bwd
(23.0 vs 15.9 ms).  XLA's isolated vocab-chain cost is already close to
the matmul roofline; the backward's +33% recompute FLOPs and this
kernel's scheduling don't buy back the logits traffic on v5e.  Together
with the standalone loss kernel's 0.43x, the conclusion is that the
GPT step's in-context vocab-chain cost (~34 ms attributed vs ~16 ms
isolated) is a global scheduling/overlap matter, not locally fusible
waste — the honest round-5 attack is program-level (e.g. loss chunking
overlapped with the next microbatch), not another kernel.  The kernel
stays as tested evidence (tests/test_lm_head_xent.py; the
``lm_head_xent`` A/B row re-measures it).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_f32 = jnp.float32
_NEG = -1e30


def _round_up(x, m):
    return (x + m - 1) // m * m


def _blocks(n, v, e):
    """(bn, bv): row/vocab block sizes.  Working set per step:
    x (bn, E) + emb (bv, E) + logits (bn, bv) in f32 — ~2.7 MB at the
    defaults with E=768."""
    bv = min(1024, _round_up(v, 128))
    bn = min(256, _round_up(n, 8))
    return bn, bv


def _fwd_kernel(x_ref, e_ref, lab_ref, loss_ref, lse_ref,
                m_scr, l_scr, t_scr, *, v, bv, nj):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        t_scr[...] = jnp.zeros_like(t_scr)

    x = x_ref[...].astype(_f32)                    # (bn, E)
    e = e_ref[...].astype(_f32)                    # (bv, E)
    s = jax.lax.dot_general(x, e, (((1,), (1,)), ((), ())),
                            preferred_element_type=_f32)   # (bn, bv)
    lab = lab_ref[...]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = cols < v
    sm = jnp.where(valid, s, _NEG)

    m_prev = m_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(sm, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_scr[...] = l_scr[...] * alpha + jnp.sum(
        jnp.exp(sm - m_new), axis=1, keepdims=True)
    m_scr[...] = m_new
    t_scr[...] += jnp.sum(jnp.where(cols == lab, s, 0.0), axis=1,
                          keepdims=True)

    @pl.when(j == nj - 1)
    def _fin():
        lse = m_scr[...] + jnp.log(l_scr[...])
        loss_ref[...] = lse - t_scr[...]
        lse_ref[...] = lse


def _dx_kernel(x_ref, e_ref, lab_ref, lse_ref, gm_ref, dx_ref, acc_scr,
               *, v, bv, nj):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(_f32)
    e = e_ref[...].astype(_f32)
    s = jax.lax.dot_general(x, e, (((1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    lab = lab_ref[...]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # vocab-pad columns: exp(s - lse) of an UNMASKED recomputed block
    # could be nonzero there; mask like the forward did
    p = jnp.where(cols < v, jnp.exp(s - lse_ref[...]), 0.0)
    dl = gm_ref[...] * (p - (cols == lab).astype(_f32))   # (bn, bv)
    acc_scr[...] += jax.lax.dot(dl, e, preferred_element_type=_f32)

    @pl.when(j == nj - 1)
    def _fin():
        dx_ref[...] = acc_scr[...].astype(dx_ref.dtype)


def _demb_kernel(x_ref, e_ref, lab_ref, lse_ref, gm_ref, de_ref, acc_scr,
                 *, v, bv, ni):
    # grid (vocab-blocks, row-blocks): rows innermost for accumulation
    j, i = pl.program_id(0), pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(_f32)
    e = e_ref[...].astype(_f32)
    s = jax.lax.dot_general(x, e, (((1,), (1,)), ((), ())),
                            preferred_element_type=_f32)
    lab = lab_ref[...]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.where(cols < v, jnp.exp(s - lse_ref[...]), 0.0)
    dl = gm_ref[...] * (p - (cols == lab).astype(_f32))
    acc_scr[...] += jax.lax.dot_general(dl, x, (((0,), (0,)), ((), ())),
                                        preferred_element_type=_f32)

    @pl.when(i == ni - 1)
    def _fin():
        de_ref[...] = acc_scr[...].astype(de_ref.dtype)


def _pad_inputs(x, emb, labels, bn, bv):
    n, e = x.shape
    v = emb.shape[0]
    n_p, v_p = _round_up(n, bn), _round_up(v, bv)
    if n_p != n:
        x = jnp.pad(x, ((0, n_p - n), (0, 0)))
    if v_p != v:
        emb = jnp.pad(emb, ((0, v_p - v), (0, 0)))
    lab2d = jnp.pad(labels.astype(jnp.int32), (0, n_p - n),
                    constant_values=-1).reshape(n_p, 1)
    return x, emb, lab2d, n_p, v_p


def _jnp_chain(x, emb, labels):
    """The production-equivalent fallback (head matmul + log-softmax CE)
    for substrates without Pallas — the package's dispatch duality."""
    logits = jnp.matmul(x, emb.T.astype(x.dtype))
    logp = jax.nn.log_softmax(logits.astype(_f32), axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]


@jax.custom_vjp
def _fused_kernel_path(x, emb, labels):
    return _fwd_impl(x, emb, labels, interpret=_interp())[0]


def fused_lm_head_xent(x, emb, labels):
    """x (N, E) activations, emb (V, E) tied table, labels (N,) int →
    per-row cross-entropy losses (N,) f32.  On a Pallas substrate the
    (N, V) logits never materialize in HBM in either pass; elsewhere the
    jnp chain runs (package dispatch duality)."""
    from .dispatch import pallas_mode
    if pallas_mode() is None:
        return _jnp_chain(x, emb, labels)
    return _fused_kernel_path(x, emb, labels)


def _fwd_impl(x, emb, labels, interpret=False):
    n, e = x.shape
    v = emb.shape[0]
    bn, bv = _blocks(n, v, e)
    xp, ep, lab2d, n_p, v_p = _pad_inputs(x, emb, labels, bn, bv)
    ni, nj = n_p // bn, v_p // bv
    x_spec = pl.BlockSpec((bn, e), lambda i, j: (i, 0))
    e_spec = pl.BlockSpec((bv, e), lambda i, j: (j, 0))
    r_spec = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    losses, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, v=v, bv=bv, nj=nj),
        grid=(ni, nj),
        in_specs=[x_spec, e_spec, r_spec],
        out_specs=[r_spec, r_spec],
        out_shape=[jax.ShapeDtypeStruct((n_p, 1), _f32)] * 2,
        scratch_shapes=[pltpu.VMEM((bn, 1), _f32)] * 3,
        interpret=interpret,
    )(xp, ep, lab2d)
    return losses[:n, 0], lse[:n, 0]


def _fwd(x, emb, labels):
    losses, lse = _fwd_impl(x, emb, labels, interpret=_interp())
    return losses, (x, emb, labels, lse)


def _interp():
    from .dispatch import pallas_mode
    return pallas_mode() == "interpret"


def _bwd(res, g):
    x, emb, labels, lse = res
    n, e = x.shape
    v = emb.shape[0]
    bn, bv = _blocks(n, v, e)
    xp, ep, lab2d, n_p, v_p = _pad_inputs(x, emb, labels, bn, bv)
    ni, nj = n_p // bn, v_p // bv
    interpret = _interp()
    # padded rows: gm 0 and lse +big -> p underflows to 0
    gm2d = jnp.pad(g.astype(_f32), (0, n_p - n)).reshape(n_p, 1)
    lse2d = jnp.pad(lse.astype(_f32), (0, n_p - n),
                    constant_values=-_NEG).reshape(n_p, 1)

    x_spec = pl.BlockSpec((bn, e), lambda i, j: (i, 0))
    e_spec = pl.BlockSpec((bv, e), lambda i, j: (j, 0))
    r_spec = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, v=v, bv=bv, nj=nj),
        grid=(ni, nj),
        in_specs=[x_spec, e_spec, r_spec, r_spec, r_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((n_p, e), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn, e), _f32)],
        interpret=interpret,
    )(xp, ep, lab2d, lse2d, gm2d)

    # swapped grid: vocab blocks outer, row blocks inner
    x_spec2 = pl.BlockSpec((bn, e), lambda j, i: (i, 0))
    e_spec2 = pl.BlockSpec((bv, e), lambda j, i: (j, 0))
    r_spec2 = pl.BlockSpec((bn, 1), lambda j, i: (i, 0))
    demb = pl.pallas_call(
        functools.partial(_demb_kernel, v=v, bv=bv, ni=ni),
        grid=(nj, ni),
        in_specs=[x_spec2, e_spec2, r_spec2, r_spec2, r_spec2],
        out_specs=e_spec2,
        out_shape=jax.ShapeDtypeStruct((v_p, e), emb.dtype),
        scratch_shapes=[pltpu.VMEM((bv, e), _f32)],
        interpret=interpret,
    )(xp, ep, lab2d, lse2d, gm2d)
    import numpy as _np

    dlab = _np.zeros(labels.shape, jax.dtypes.float0)
    return dx[:n], demb[:v], dlab


_fused_kernel_path.defvjp(_fwd, _bwd)
