"""Fused multi-tensor optimizer update kernels.

The ``ops.multi_tensor_*`` suite is a Python loop over tensors — XLA
fuses each bucket's elementwise chain, but every tensor is its own
fusion with its own HBM round trip and the loop body retraces per
bucket.  This module packs a whole parameter group into one
``(rows, 128)`` f32 panel (cast → ravel → concat → pad) and runs the
update as ONE Pallas kernel over a 1-D row-block grid, then unpacks,
casts back per-tensor and applies the ``noop_flag`` skip outside the
kernel — the reference CUDA design (``multi_tensor_apply.cuh`` packs
110 pointers per launch) re-expressed for TPU.

Parity is bitwise in fp32 BY CONSTRUCTION, not by tolerance: the kernel
body performs the identical elementwise op chain in the identical order
as the per-bucket loop (``ops/multi_tensor.py``), every derived scalar
(1-beta, bias corrections) is computed OUTSIDE with the exact
per-bucket expression and enters through SMEM as f32 — the same
rounding a weak Python float gets under promotion — and pack/unpack is
pure data movement.  ``tests/test_kernels.py`` pins this.

Dispatch: like the norm kernels (round-5 receipt: 0.93-1.03x — XLA
fuses elementwise chains well on its own), the fused update is
UNPROVEN on compiled TPU, so the registered threshold probe defaults to
XLA there; a ledger entry with a measured win flips it.  Interpret mode
always exercises the kernel — that mode exists to test it.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import dispatch as _dispatch

_f32 = jnp.float32
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _static_nonzero(x) -> bool:
    # mirrors ops.multi_tensor._static_nonzero (imported lazily there to
    # avoid a cycle; 2 lines is cheaper than the import dance)
    return not (isinstance(x, (int, float)) and x == 0.0)


def _block_rows(rows: int) -> int:
    """Sublane-aligned row block, balanced so padding stays bounded."""
    br = min(256, _round_up(max(rows, 1), 8))
    nblocks = -(-rows // br)
    return min(br, _round_up(-(-rows // nblocks), 8))


def _pack(tensors):
    """Cast-to-f32, ravel, concat and pad into a (rows, 128) panel.

    Elementwise-update parity survives packing: concat of elementwise
    ops == elementwise op of the concat, and padded tail elements are
    sliced off at unpack.
    """
    flat = [t.astype(_f32).ravel() for t in tensors]
    total = sum(f.size for f in flat)
    rows = -(-max(total, 1) // _LANES)
    br = _block_rows(rows)
    rows_p = _round_up(rows, br)
    buf = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
    pad = rows_p * _LANES - total
    if pad:
        buf = jnp.pad(buf, (0, pad))
    return buf.reshape(rows_p, _LANES), br


def _unpack(panel, tensors):
    """Slice the f32 panel back into the tensors' shapes (still f32 —
    the caller owns the dtype cast and the noop skip, exactly like the
    per-bucket loop's epilogue)."""
    flat = panel.ravel()
    out, off = [], 0
    for t in tensors:
        out.append(flat[off:off + t.size].reshape(t.shape))
        off += t.size
    return out


def group_fp(op: str, tensors) -> str:
    """Ledger fingerprint for one packed group."""
    dtype = {str(t.dtype) for t in tensors}
    return _dispatch.multi_tensor_fp(
        op, sum(t.size for t in tensors), len(tensors),
        dtype.pop() if len(dtype) == 1 else "mixed")


# ---------------------------------------------------------------------------
# SGD
# ---------------------------------------------------------------------------

# SMEM scalar slots (all f32; derived values precomputed outside)
_SGD_LR, _SGD_WD, _SGD_SCALE = 0, 1, 2


def _sgd_kernel(g_ref, p_ref, m_ref, scal_ref, np_ref, nm_ref, *,
                momentum, dampening, nesterov, first_run,
                wd_after_momentum, use_wd):
    # op order is ops.multi_tensor.multi_tensor_sgd's loop body, verbatim
    gf = g_ref[...] * scal_ref[_SGD_SCALE]
    pf = p_ref[...]
    if use_wd and not wd_after_momentum:
        gf = gf + scal_ref[_SGD_WD] * pf
    if momentum != 0.0:
        if first_run:
            mf = gf
        else:
            mf = momentum * m_ref[...] + (1.0 - dampening) * gf
        upd = gf + momentum * mf if nesterov else mf
    else:
        mf = m_ref[...]
        upd = gf
    if use_wd and wd_after_momentum:
        upd = upd + scal_ref[_SGD_WD] * pf
    np_ref[...] = pf - scal_ref[_SGD_LR] * upd
    nm_ref[...] = mf


def fused_sgd(noop_flag, tensor_lists, wd, momentum, dampening, lr,
              nesterov: bool, first_run: bool, wd_after_momentum: bool,
              scale=1.0):
    """Drop-in for ``ops.multi_tensor_sgd`` (depth 3 or 4) as one packed
    Pallas pass.  Same returns, same ``noop_flag`` skip semantics."""
    depth = len(tensor_lists)
    if depth == 3:
        gs, ps, ms = tensor_lists
        model_ps = None
    elif depth == 4:
        gs, ps, ms, model_ps = tensor_lists
    else:
        raise ValueError(f"fused_sgd supports depth 3 or 4, got {depth}")
    if not gs:
        return (noop_flag, [], [], []) if model_ps is not None else \
            (noop_flag, [], [])

    use_wd = _static_nonzero(wd)
    momentum = float(momentum)
    dampening = float(dampening)
    g_pack, br = _pack(gs)
    p_pack, _ = _pack(ps)
    m_pack, _ = _pack(ms)
    scal = jnp.stack([jnp.asarray(lr, _f32),
                      jnp.asarray(wd if use_wd else 0.0, _f32),
                      jnp.asarray(scale, _f32)])
    blk = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    new_p_pack, new_m_pack = pl.pallas_call(
        functools.partial(
            _sgd_kernel, momentum=momentum, dampening=dampening,
            nesterov=bool(nesterov), first_run=bool(first_run),
            wd_after_momentum=bool(wd_after_momentum), use_wd=use_wd),
        grid=(g_pack.shape[0] // br,),
        in_specs=[blk, blk, blk, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[blk, blk],
        out_shape=[jax.ShapeDtypeStruct(g_pack.shape, _f32)] * 2,
        interpret=_dispatch.pallas_mode() == "interpret",
    )(g_pack, p_pack, m_pack, scal)

    skip = noop_flag > 0
    pfs = _unpack(new_p_pack, ps)
    mfs = _unpack(new_m_pack, ms)
    new_ps = [jnp.where(skip, p, pf.astype(p.dtype))
              for p, pf in zip(ps, pfs)]
    new_ms = [jnp.where(skip, m, mf.astype(m.dtype))
              for m, mf in zip(ms, mfs)]
    if model_ps is not None:
        new_model = [jnp.where(skip, mp, pf.astype(mp.dtype))
                     for mp, pf in zip(model_ps, pfs)]
        return noop_flag, new_ps, new_ms, new_model
    return noop_flag, new_ps, new_ms


# ---------------------------------------------------------------------------
# Adam / AdamW
# ---------------------------------------------------------------------------

_AD_LR, _AD_WD, _AD_B1, _AD_OMB1, _AD_B2, _AD_OMB2, _AD_EPS, \
    _AD_BC1, _AD_BC2 = range(9)


def _adam_kernel(g_ref, p_ref, m_ref, v_ref, scal_ref,
                 np_ref, nm_ref, nv_ref, *, decoupled, use_wd):
    gf = g_ref[...]
    pf = p_ref[...]
    if use_wd and not decoupled:           # ADAM_MODE_L2
        gf = gf + scal_ref[_AD_WD] * pf
    mf = scal_ref[_AD_B1] * m_ref[...] + scal_ref[_AD_OMB1] * gf
    vf = scal_ref[_AD_B2] * v_ref[...] + scal_ref[_AD_OMB2] * gf * gf
    update = (mf / scal_ref[_AD_BC1]) / (
        jnp.sqrt(vf / scal_ref[_AD_BC2]) + scal_ref[_AD_EPS])
    if use_wd and decoupled:               # ADAM_MODE_DECOUPLED
        update = update + scal_ref[_AD_WD] * pf
    np_ref[...] = pf - scal_ref[_AD_LR] * update
    nm_ref[...] = mf
    nv_ref[...] = vf


def fused_adam(noop_flag, tensor_lists, lr, beta1, beta2, eps, step,
               mode: int, bias_correction: bool, weight_decay):
    """Drop-in for ``ops.multi_tensor_adam`` as one packed Pallas pass.
    Propagates infs/nans without flag writes, like the reference."""
    gs, ps, ms, vs = tensor_lists
    if not gs:
        return noop_flag, [], [], []
    # bias correction and 1-beta computed with the EXACT per-bucket
    # expressions (host-side when step/beta are Python numbers) so the
    # f32 values entering SMEM match weak-promotion rounding bitwise
    if bias_correction:
        if isinstance(step, (int, float)):
            bc1 = 1.0 - beta1 ** step
            bc2 = 1.0 - beta2 ** step
        else:
            stepf = jnp.asarray(step, _f32)
            bc1 = 1.0 - jnp.asarray(beta1, _f32) ** stepf
            bc2 = 1.0 - jnp.asarray(beta2, _f32) ** stepf
    else:
        bc1 = bc2 = 1.0
    omb1 = 1.0 - beta1
    omb2 = 1.0 - beta2
    use_wd = _static_nonzero(weight_decay)

    g_pack, br = _pack(gs)
    p_pack, _ = _pack(ps)
    m_pack, _ = _pack(ms)
    v_pack, _ = _pack(vs)
    scal = jnp.stack([jnp.asarray(v, _f32) for v in (
        lr, weight_decay if use_wd else 0.0, beta1, omb1, beta2, omb2,
        eps, bc1, bc2)])
    blk = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    new_p, new_m, new_v = pl.pallas_call(
        functools.partial(_adam_kernel, decoupled=mode == 1,
                          use_wd=use_wd),
        grid=(g_pack.shape[0] // br,),
        in_specs=[blk, blk, blk, blk,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct(g_pack.shape, _f32)] * 3,
        interpret=_dispatch.pallas_mode() == "interpret",
    )(g_pack, p_pack, m_pack, v_pack, scal)

    new_ps = [pf.astype(p.dtype) for p, pf in zip(ps, _unpack(new_p, ps))]
    new_ms = [mf.astype(m.dtype) for m, mf in zip(ms, _unpack(new_m, ms))]
    new_vs = [vf.astype(v.dtype) for v, vf in zip(vs, _unpack(new_v, vs))]
    return noop_flag, new_ps, new_ms, new_vs


# ---------------------------------------------------------------------------
# Registration + the executor-dispatched eager entries
# ---------------------------------------------------------------------------


def _elementwise_probe(dims):
    # the norm-kernel lesson generalized: XLA fuses elementwise chains
    # near-roofline on its own, so an unmeasured fused update defaults
    # to XLA on compiled backends; a ledger win flips it per shape
    return None, False


def _audit_tensor_lists(depth):
    import jax
    sds = jax.ShapeDtypeStruct
    f32 = jnp.float32
    return [[sds((8, 4), f32), sds((16,), f32)] for _ in range(depth)]


def _audit_flag():
    import jax
    return jax.ShapeDtypeStruct((), jnp.int32)


def _sgd_audit_programs():
    from ..ops import multi_tensor as _ops

    def _pallas(flag, tl):
        return fused_sgd(flag, tl, 0.0, 0.9, 0.0, 0.05, False, True, False)

    def _xla(flag, tl):
        return _ops.sgd_unfused(flag, tl, 0.0, 0.9, 0.0, 0.05,
                                False, True, False)

    args = (_audit_flag(), _audit_tensor_lists(3))
    return [("pallas", _pallas, args), ("xla", _xla, args)]


def _adam_audit_programs():
    from ..ops import multi_tensor as _ops

    def _pallas(flag, tl):
        return fused_adam(flag, tl, 1e-3, 0.9, 0.999, 1e-8, 1, 0,
                          True, 0.0)

    def _xla(flag, tl):
        return _ops.adam_unfused(flag, tl, 1e-3, 0.9, 0.999, 1e-8, 1, 0,
                                 True, 0.0)

    args = (_audit_flag(), _audit_tensor_lists(4))
    return [("pallas", _pallas, args), ("xla", _xla, args)]


_dispatch.register_kernel(
    "multi_tensor_sgd",
    xla_fallback="apex_tpu.ops.multi_tensor.sgd_unfused",
    threshold_probe=_elementwise_probe,
    doc="Packed momentum-SGD group update (fused_sgd)",
    audit_programs=_sgd_audit_programs)

_dispatch.register_kernel(
    "multi_tensor_adam",
    xla_fallback="apex_tpu.ops.multi_tensor.adam_unfused",
    threshold_probe=_elementwise_probe,
    doc="Packed Adam/AdamW group update (fused_adam)",
    audit_programs=_adam_audit_programs)


def multi_tensor_sgd(noop_flag, tensor_lists, wd, momentum, dampening, lr,
                     nesterov: bool, first_run: bool,
                     wd_after_momentum: bool, scale=1.0):
    """Eager executor-dispatched SGD group update: the tier decision
    becomes the Program kind (``kernel.multi_tensor_sgd.<tier>``) so
    ``step_cache.kind_stats`` pins which path ran.  Donation-safe: the
    tensor lists are donated under the one DonationPolicy.  Hyperparams
    must be Python numbers here (they join the static key)."""
    from ..ops import multi_tensor as _ops

    hyper = (float(wd), float(momentum), float(dampening), float(lr),
             bool(nesterov), bool(first_run), bool(wd_after_momentum),
             float(scale))

    def pallas_fn(flag, lists):
        return fused_sgd(flag, lists, *hyper)

    def xla_fn(flag, lists):
        return _ops.sgd_unfused(flag, lists, *hyper)

    return _dispatch.run(
        "multi_tensor_sgd", group_fp("sgd", tensor_lists[0]),
        (noop_flag, tensor_lists), pallas_fn=pallas_fn, xla_fn=xla_fn,
        static_key=hyper, donate_argnums=(1,))


def multi_tensor_adam(noop_flag, tensor_lists, lr, beta1, beta2, eps,
                      step, mode: int, bias_correction: bool,
                      weight_decay):
    """Eager executor-dispatched Adam/AdamW group update (see
    :func:`multi_tensor_sgd` for the dispatch semantics)."""
    from ..ops import multi_tensor as _ops

    hyper = (float(lr), float(beta1), float(beta2), float(eps),
             int(step), int(mode), bool(bias_correction),
             float(weight_decay))

    def pallas_fn(flag, lists):
        return fused_adam(flag, lists, *hyper)

    def xla_fn(flag, lists):
        return _ops.adam_unfused(flag, lists, *hyper)

    return _dispatch.run(
        "multi_tensor_adam", group_fp("adam", tensor_lists[0]),
        (noop_flag, tensor_lists), pallas_fn=pallas_fn, xla_fn=xla_fn,
        static_key=hyper, donate_argnums=(1,))
