"""Pallas TPU layer-norm kernels.

TPU re-design of the reference's ``fused_layer_norm_cuda`` extension
(csrc/layer_norm_cuda.cpp:133-241, csrc/layer_norm_cuda_kernel.cu): forward
returns ``(out, mean, invvar)`` with fp32 statistics regardless of input
dtype; backward consumes the saved stats and returns ``dx[, dgamma, dbeta]``.

Kernel layout: rows (the product of non-normalized dims) are blocked over a
1-D sequential grid; the whole normalized dim sits in the lane dimension of
one VMEM block, so per-row stats are a single in-register reduction (no
Welford needed — unlike the CUDA kernel we never split a row across blocks).
``dgamma``/``dbeta`` are accumulated across grid steps into one (1, N)
output block, relying on the TPU grid's sequential execution order.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_f32 = jnp.float32


def _block_rows(rows: int, n: int) -> int:
    """Rows per block: ~512K fp32 elements of x per block, sublane-aligned,
    then balanced across the grid so row padding is bounded by 15 rows
    (e.g. rows=528 gets 2x272-row blocks, not 2x512)."""
    bm = max(16, min(512, (1 << 19) // max(n, 1) // 16 * 16))
    nblocks = -(-rows // bm)
    return min(bm, _round_up(-(-rows // nblocks), 16))


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _fwd_kernel(x_ref, *refs, eps, affine):
    if affine:
        w_ref, b_ref, y_ref, mean_ref, rstd_ref = refs
    else:
        y_ref, mean_ref, rstd_ref = refs
    x = x_ref[...].astype(_f32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd
    if affine:
        y = y * w_ref[...].astype(_f32) + b_ref[...].astype(_f32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _bwd_kernel(g_ref, x_ref, mean_ref, rstd_ref, *refs, affine):
    if affine:
        w_ref, dx_ref, dw_ref, db_ref = refs
    else:
        (dx_ref,) = refs
    g = g_ref[...].astype(_f32)
    xhat = (x_ref[...].astype(_f32) - mean_ref[...]) * rstd_ref[...]
    gh = g * w_ref[...].astype(_f32) if affine else g
    c1 = jnp.mean(gh, axis=1, keepdims=True)
    c2 = jnp.mean(gh * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((gh - c1 - xhat * c2) * rstd_ref[...]).astype(dx_ref.dtype)
    if affine:
        @pl.when(pl.program_id(0) == 0)
        def _init():
            dw_ref[...] = jnp.zeros_like(dw_ref)
            db_ref[...] = jnp.zeros_like(db_ref)
        dw_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)
        db_ref[...] += jnp.sum(g, axis=0, keepdims=True)


def ln_forward(x2d, weight, bias, eps, interpret=False):
    """x2d (rows, N); weight/bias (N,) or None. → (y, mean, rstd), stats
    fp32 with shape (rows, 1)."""
    rows, n = x2d.shape
    affine = weight is not None
    bm = _block_rows(rows, n)
    rows_p = _round_up(rows, bm)
    if rows_p != rows:
        x2d = jnp.pad(x2d, ((0, rows_p - rows), (0, 0)))
    row_spec = pl.BlockSpec((bm, n), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((bm, 1), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, n), lambda i: (0, 0))
    args = [x2d]
    in_specs = [row_spec]
    if affine:
        args += [weight.reshape(1, n), bias.reshape(1, n)]
        in_specs += [vec_spec, vec_spec]
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, affine=affine),
        grid=(rows_p // bm,),
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, n), x2d.dtype),
            jax.ShapeDtypeStruct((rows_p, 1), _f32),
            jax.ShapeDtypeStruct((rows_p, 1), _f32),
        ],
        interpret=interpret,
    )(*args)
    return y[:rows], mean[:rows], rstd[:rows]


def ln_backward(g2d, x2d, mean, rstd, weight, interpret=False):
    """→ dx (and, when affine, dgamma/dbeta in fp32, shape (N,))."""
    rows, n = x2d.shape
    affine = weight is not None
    bm = _block_rows(rows, n)
    rows_p = _round_up(rows, bm)
    if rows_p != rows:
        # zero-padded g rows contribute nothing to dgamma/dbeta
        g2d = jnp.pad(g2d, ((0, rows_p - rows), (0, 0)))
        x2d = jnp.pad(x2d, ((0, rows_p - rows), (0, 0)))
        mean = jnp.pad(mean, ((0, rows_p - rows), (0, 0)))
        rstd = jnp.pad(rstd, ((0, rows_p - rows), (0, 0)), constant_values=1.0)
    row_spec = pl.BlockSpec((bm, n), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((bm, 1), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, n), lambda i: (0, 0))
    args = [g2d, x2d, mean, rstd]
    in_specs = [row_spec, row_spec, stat_spec, stat_spec]
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows_p, n), x2d.dtype)]
    if affine:
        args.append(weight.reshape(1, n))
        in_specs.append(vec_spec)
        out_specs += [vec_spec, vec_spec]
        out_shape += [jax.ShapeDtypeStruct((1, n), _f32)] * 2
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, affine=affine),
        grid=(rows_p // bm,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    if affine:
        dx, dw, db = outs
        return dx[:rows], dw.reshape(n), db.reshape(n)
    return (outs[0][:rows],)
