"""On-disk calibration ledger: measured kernel A/Bs and plan trials,
keyed by ``(chip, fingerprint)`` — ROADMAP item 2.

The round-5 verdicts (norms 0.93-1.03x -> XLA default, flash only
>= 512 keys, lm_head_xent 0.69x) were frozen into code as constants and
env knobs; every new chip or shape regime would re-litigate them by
hand.  This ledger is where those receipts live as *data*: ``bench.py
--kernels`` probe records and ``observe`` events (``plan.auto_tune``,
``plan.decision``) persist into one JSON document, the dispatch policy
(:mod:`apex_tpu.kernels.dispatch`) reads kernel entries at trace time,
and the planner (:mod:`apex_tpu.parallel.auto`) re-ranks repeated runs
from plan entries instead of roofline priors — the measured-not-priors
loop Galvatron (arXiv:2504.03662) and Colossal-Auto (arXiv:2302.02599)
both argue cost models need.

File format (``docs/kernels.md`` carries the full description)::

    {"version": 1,
     "kernels": {chip: {kernel: {shape_fp: {pallas_us, xla_us, win,
                                            threshold, source, runs}}}},
     "plans":   {chip: {model_fp: {plan_key: {measured_ms, predicted_ms,
                                              plan, source, runs}}}}}

Writes are atomic (tmp + ``os.replace``) and loads are defensive: a
corrupt file or a corrupt entry is skipped, never fatal — a half-written
ledger must not take training down (the checkpoint lesson, CKPT-ATOMIC).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional

_ENV_PATH = "APEX_TPU_LEDGER"
_VERSION = 1


def default_path() -> str:
    """``$APEX_TPU_LEDGER`` or ``~/.cache/apex_tpu/kernel_ledger.json``."""
    env = os.environ.get(_ENV_PATH)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "apex_tpu",
                        "kernel_ledger.json")


def chip_name(devices=None) -> str:
    """The ledger's chip key: the device kind ("TPU v5e", "cpu", ...).
    Entries measured on one chip never price another."""
    import jax
    ds = list(devices) if devices is not None else jax.devices()
    if not ds:
        return "cpu"
    return (getattr(ds[0], "device_kind", "") or ds[0].platform or
            "cpu")


def _win(pallas_us, xla_us) -> Optional[float]:
    if not pallas_us or not xla_us or pallas_us <= 0:
        return None
    return xla_us / pallas_us


def _plan_key_str(plan_key) -> str:
    """Normalize a ``Plan.key()`` tuple (or a string) to the ledger's
    string key — JSON object keys must be strings."""
    if isinstance(plan_key, str):
        return plan_key

    def seg(x):
        if isinstance(x, bool):
            return "1" if x else "0"
        if isinstance(x, str):      # tagged v3 segments ("pp4", "remat=…")
            return x
        return str(int(x))

    return "/".join(seg(x) for x in plan_key)


class Ledger:
    """One calibration document, loaded lazily and written atomically.

    Thread-safe; every mutation persists immediately (probe records are
    rare — bench stages and auto-tune trials, never per-step paths).
    """

    _KERNEL_FIELDS = ("pallas_us", "xla_us", "win", "threshold",
                      "source", "runs")

    def __init__(self, path: Optional[str] = None):
        self._path = path or default_path()
        self._lock = threading.RLock()
        self._doc = None                 # loaded lazily

    @property
    def path(self) -> str:
        return self._path

    # -- load / save -------------------------------------------------------

    def _empty(self) -> dict:
        return {"version": _VERSION, "kernels": {}, "plans": {}}

    def _load(self) -> dict:
        if self._doc is not None:
            return self._doc
        doc = self._empty()
        try:
            with open(self._path) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            # missing or corrupt file: start empty (never fatal — the
            # ledger is a cache of measurements, not a source of truth)
            raw = None
        if isinstance(raw, dict):
            for section in ("kernels", "plans"):
                sec = raw.get(section)
                if isinstance(sec, dict):
                    doc[section] = self._sanitize(sec)
        self._doc = doc
        return doc

    @staticmethod
    def _sanitize(section: dict) -> dict:
        """Keep only well-formed chip -> key -> fp -> dict(record)
        entries; a corrupt entry is dropped, not propagated."""
        out = {}
        for chip, by_name in section.items():
            if not isinstance(by_name, dict):
                continue
            for name, by_fp in by_name.items():
                if not isinstance(by_fp, dict):
                    continue
                for fp, rec in by_fp.items():
                    if not isinstance(rec, dict):
                        continue
                    out.setdefault(str(chip), {}).setdefault(
                        str(name), {})[str(fp)] = rec
        return out

    def _save(self) -> None:
        doc = self._load()
        d = os.path.dirname(self._path)
        try:
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self._path)
        except OSError:
            # unwritable ledger path: keep the in-memory doc, stay quiet
            # (read-only containers run the same code)
            pass

    def reload(self) -> None:
        with self._lock:
            self._doc = None
            self._load()

    # -- kernel entries ----------------------------------------------------

    def record_kernel(self, chip: str, kernel: str, shape_fp: str, *,
                      pallas_us=None, xla_us=None, threshold=None,
                      source: str = "bench") -> dict:
        """Insert/refresh one kernel probe record; returns the record."""
        with self._lock:
            doc = self._load()
            by_fp = doc["kernels"].setdefault(str(chip), {}).setdefault(
                str(kernel), {})
            prev = by_fp.get(str(shape_fp), {})
            rec = {
                "pallas_us": pallas_us, "xla_us": xla_us,
                "win": _win(pallas_us, xla_us),
                "threshold": threshold, "source": source,
                "runs": int(prev.get("runs", 0)) + 1,
            }
            by_fp[str(shape_fp)] = rec
            self._save()
            return rec

    def lookup_kernel(self, chip: str, kernel: str,
                      shape_fp: str) -> Optional[dict]:
        with self._lock:
            rec = self._load()["kernels"].get(str(chip), {}).get(
                str(kernel), {}).get(str(shape_fp))
            # a record without a usable win ratio cannot decide dispatch
            if rec is None or _win(rec.get("pallas_us"),
                                   rec.get("xla_us")) is None:
                return None
            return dict(rec, win=_win(rec["pallas_us"], rec["xla_us"]),
                        shape_fp=str(shape_fp), kernel=str(kernel),
                        chip=str(chip))

    def kernel_entries(self, chip: str, kernel: str) -> dict:
        """``{shape_fp: record}`` snapshot for one (chip, kernel)."""
        with self._lock:
            by_fp = self._load()["kernels"].get(str(chip), {}).get(
                str(kernel), {})
            return {fp: dict(rec) for fp, rec in by_fp.items()}

    # -- plan entries ------------------------------------------------------

    def record_plan(self, chip: str, model_fp: str, plan_key, *,
                    measured_ms=None, predicted_ms=None, plan=None,
                    source: str = "auto_tune") -> dict:
        with self._lock:
            doc = self._load()
            by_key = doc["plans"].setdefault(str(chip), {}).setdefault(
                str(model_fp), {})
            key = _plan_key_str(plan_key)
            prev = by_key.get(key, {})
            rec = {
                "measured_ms": measured_ms,
                "predicted_ms": predicted_ms,
                "plan": plan, "source": source,
                "runs": int(prev.get("runs", 0)) + 1,
            }
            if measured_ms is None and prev.get("measured_ms") is not None:
                rec["measured_ms"] = prev["measured_ms"]   # keep the data
            by_key[key] = rec
            self._save()
            return rec

    def plan_measurements(self, chip: str, model_fp: str) -> dict:
        """``{plan_key_str: record}`` with a measured_ms, for re-ranking."""
        with self._lock:
            by_key = self._load()["plans"].get(str(chip), {}).get(
                str(model_fp), {})
            return {k: dict(r) for k, r in by_key.items()
                    if isinstance(r.get("measured_ms"), (int, float))}

    # -- event ingestion ---------------------------------------------------

    def ingest_events(self, events) -> int:
        """Fold observe event records into the ledger.

        Consumes ``bench.kernel_probe`` records (kernel timings) and
        ``plan.auto_tune`` / ``plan.decision`` events that carry
        ``chip`` + ``model_fp`` (the planner stamps both).  Returns the
        number of entries absorbed; unknown or incomplete events are
        skipped — the event log is append-only telemetry, not a schema
        contract.
        """
        n = 0
        for ev in events:
            if not isinstance(ev, dict):
                continue
            name = ev.get("event") or ev.get("name") or ev.get("metric")
            if name in ("bench.kernel_probe", "kernel_probe"):
                if ev.get("kernel") and ev.get("shape_fp"):
                    self.record_kernel(
                        ev.get("chip") or chip_name(),
                        ev["kernel"], ev["shape_fp"],
                        pallas_us=ev.get("pallas_us"),
                        xla_us=ev.get("xla_us"),
                        threshold=ev.get("threshold"),
                        source="bench")
                    n += 1
            elif name in ("plan.auto_tune", "plan.decision"):
                if ev.get("chip") and ev.get("model_fp") and \
                        ev.get("plan_key") is not None and \
                        ev.get("measured_ms") is not None:
                    self.record_plan(
                        ev["chip"], ev["model_fp"], tuple(ev["plan_key"]),
                        measured_ms=ev.get("measured_ms"),
                        predicted_ms=ev.get("predicted_ms"),
                        plan=ev.get("plan"), source=name)
                    n += 1
        return n


# -- process-global ledger ---------------------------------------------------

_global = [None]
_global_lock = threading.Lock()


def get_ledger() -> Ledger:
    """The process ledger at :func:`default_path` (override with
    :func:`set_path` — tests point it at a tmp file)."""
    with _global_lock:
        if _global[0] is None:
            _global[0] = Ledger()
        return _global[0]


def set_path(path: Optional[str]) -> Ledger:
    """Re-point the process ledger (None restores the default path).
    Returns the fresh ledger."""
    with _global_lock:
        _global[0] = Ledger(path)
        return _global[0]
