"""Pallas TPU fused label-smoothed softmax cross-entropy.

TPU re-design of the reference's ``xentropy_cuda`` extension
(apex/contrib/csrc/xentropy/xentropy_kernel.cu): ONE pass over each
logits row computes max / sum-exp / target-logit / row-sum in VMEM with
the bf16→f32 cast applied block-locally (free in-register), and the
backward is a single elementwise pass reconstructing probabilities from
the saved logsumexp.  The jnp expression of the same math
(contrib/xentropy/softmax_xentropy.py) can materialize f32 casts of the
whole (rows, vocab) logits in unfavorable fusion contexts — measured
~14 ms of convert_element_type per GPT seq-128 step (BENCH_HISTORY
round 4); this kernel was built to fuse that away (see VERDICT below
for how that bet measured out).

Grid: (row_blocks, col_blocks) with columns INNERMOST — running
max/denominator/target/sum scratch lives in VMEM across the column
sweep (the flash-attention pattern, ops/pallas/attention.py).  The
backward needs no scratch: ``p = exp(x - lse)`` is elementwise given
the saved per-row lse, and the label column folds in as an iota
compare.

VERDICT (round-4 on-chip A/B, BENCH_HISTORY): the kernel LOSES to
XLA's fused lowering of the jnp expression in isolation — 0.38x at
(8192, 50257), 0.74x at (16384, 50257) fwd+bwd — the online-softmax
column sweep is VPU-bound where XLA's reduce kernels are tuned, and
the GPT seq-128 headline ran 8% slower with it engaged.  Dispatch
(contrib/xentropy/softmax_xentropy._use_kernel) therefore defaults it
OFF on-chip; interpret mode always exercises it, and
APEX_TPU_XENT_KERNEL=1 opts in.  It remains the starting point for a
future fused lm-head+loss kernel (where the matmul would amortize the
sweep).

Invalid-label semantics (garbage-in divergence from the jnp path):
a label >= C matches no column in the iota compare, so the kernel
accumulates target-logit 0 (loss = lse), while the jnp path's
``lf[label]`` gather clamps to the LAST column under jit; a negative
label other than padding_idx likewise accumulates 0 here but clamps to
column 0 there.  Neither arm can raise under trace — callers must
validate label ranges (the model families do: emittable-id checks use
the logical vocab).  Smoothing is mask-aware, matching the jnp path:
columns at or below MASKED_LOGIT_THR are excluded from the smoothing
sum and its divisor in both passes, so lane-padded heads are exact
under smoothing on this arm too.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .dispatch import MASKED_LOGIT_THR as _MASK_THR

_f32 = jnp.float32
_NEG = -1e30


def _round_up(x, m):
    return (x + m - 1) // m * m


def _block_sizes(rows, c):
    """(bm, bc): ~2 MB f32 of logits per grid step, lane/sublane aligned;
    bm capped by the (padded) row count so small inputs aren't blown up
    to a 256-row block."""
    bc = min(2048, _round_up(c, 128))
    bm = max(8, min(256, (1 << 19) // bc // 8 * 8, _round_up(rows, 8)))
    return bm, bc


def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, m_scr, l_scr, t_scr,
                s_scr, n_scr, *, c, bc, nj, smoothing, padding_idx):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        t_scr[...] = jnp.zeros_like(t_scr)
        s_scr[...] = jnp.zeros_like(s_scr)
        n_scr[...] = jnp.zeros_like(n_scr)

    x = x_ref[...].astype(_f32)
    lab = lab_ref[...]                                    # (bm, 1) int32
    cols = j * bc + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = cols < c
    xm = jnp.where(valid, x, _NEG)

    m_prev = m_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(xm, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_scr[...] = l_scr[...] * alpha + jnp.sum(
        jnp.exp(xm - m_new), axis=1, keepdims=True)
    m_scr[...] = m_new
    # the label column appears in exactly one block; a padding label
    # (never a valid column id) simply accumulates nothing
    t_scr[...] += jnp.sum(jnp.where(cols == lab, x, 0.0), axis=1,
                          keepdims=True)
    # smoothing sum/count over LIVE columns only — in-range AND above
    # the masked-vocab threshold — matching the jnp path's mask-aware
    # smoothing (lane-padded heads' -1e30 columns carry no mass)
    live = valid & (x > _MASK_THR)
    s_scr[...] += jnp.sum(jnp.where(live, x, 0.0), axis=1, keepdims=True)
    n_scr[...] += jnp.sum(live.astype(_f32), axis=1, keepdims=True)

    @pl.when(j == nj - 1)
    def _fin():
        lse = m_scr[...] + jnp.log(l_scr[...])
        loss = lse - (1.0 - smoothing) * t_scr[...] \
            - smoothing * s_scr[...] / jnp.maximum(n_scr[...], 1.0)
        loss_ref[...] = jnp.where(lab == padding_idx, 0.0, loss)
        lse_ref[...] = lse


def _bwd_kernel(x_ref, lab_ref, lse_ref, gm_ref, nv_ref, dx_ref, *, c, bc,
                smoothing):
    j = pl.program_id(1)
    x = x_ref[...].astype(_f32)
    lab = lab_ref[...]
    gm = gm_ref[...]
    cols = j * bc + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    probs = jnp.exp(x - lse_ref[...])
    onehot = (cols == lab).astype(_f32)
    # mask-aware smoothing term: s/n_valid on live columns, 0 on masked
    # ones (their probs already underflow to 0, so dx there is exactly
    # 0); nv comes precomputed per row from the wrapper
    smooth = jnp.where(x > _MASK_THR, smoothing / nv_ref[...], 0.0)
    dx = gm * (probs - smooth) - ((1.0 - smoothing) * gm) * onehot
    dx_ref[...] = dx.astype(dx_ref.dtype)


def xent_forward(logits2d, labels, smoothing, padding_idx, interpret=False):
    """logits2d (rows, C), labels (rows,) int32 →
    (losses (rows,) f32, lse (rows,) f32)."""
    rows, c = logits2d.shape
    bm, bc = _block_sizes(rows, c)
    rows_p, c_p = _round_up(rows, bm), _round_up(c, bc)
    if rows_p != rows or c_p != c:
        logits2d = jnp.pad(logits2d, ((0, rows_p - rows), (0, c_p - c)))
    lab2d = jnp.pad(labels.astype(jnp.int32),
                    (0, rows_p - rows)).reshape(rows_p, 1)
    nj = c_p // bc
    row_spec = pl.BlockSpec((bm, bc), lambda i, j: (i, j))
    lab_spec = pl.BlockSpec((bm, 1), lambda i, j: (i, 0))
    losses, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, c=c, bc=bc, nj=nj,
                          smoothing=smoothing, padding_idx=padding_idx),
        grid=(rows_p // bm, nj),
        in_specs=[row_spec, lab_spec],
        out_specs=[lab_spec, lab_spec],
        out_shape=[jax.ShapeDtypeStruct((rows_p, 1), _f32)] * 2,
        scratch_shapes=[pltpu.VMEM((bm, 1), _f32)] * 5,
        interpret=interpret,
    )(logits2d, lab2d)
    return losses[:rows, 0], lse[:rows, 0]


def xent_backward(logits2d, labels, lse, gmask, smoothing, interpret=False):
    """→ dlogits (rows, C) in logits2d.dtype.  ``gmask`` (rows,) f32 is
    the incoming cotangent with padding rows already zeroed."""
    rows, c = logits2d.shape
    bm, bc = _block_sizes(rows, c)
    rows_p, c_p = _round_up(rows, bm), _round_up(c, bc)
    if smoothing:
        # per-row live-column count for the mask-aware smoothing divisor
        # (== c for unmasked inputs); one cheap reduction, smoothing-only
        nv = jnp.sum((logits2d.astype(_f32) > _MASK_THR).astype(_f32),
                     axis=-1)
    else:
        nv = jnp.full((rows,), float(c), _f32)
    if rows_p != rows or c_p != c:
        logits2d = jnp.pad(logits2d, ((0, rows_p - rows), (0, c_p - c)))
    lab2d = jnp.pad(labels.astype(jnp.int32),
                    (0, rows_p - rows)).reshape(rows_p, 1)
    # padded rows: lse -> +big so probs underflow to 0 (and gm is 0)
    lse2d = jnp.pad(lse.astype(_f32), (0, rows_p - rows),
                    constant_values=-_NEG).reshape(rows_p, 1)
    gm2d = jnp.pad(gmask.astype(_f32), (0, rows_p - rows)).reshape(rows_p, 1)
    nv2d = jnp.pad(nv, (0, rows_p - rows),
                   constant_values=1.0).reshape(rows_p, 1)
    row_spec = pl.BlockSpec((bm, bc), lambda i, j: (i, j))
    lab_spec = pl.BlockSpec((bm, 1), lambda i, j: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, c=c, bc=bc, smoothing=smoothing),
        grid=(rows_p // bm, c_p // bc),
        in_specs=[row_spec, lab_spec, lab_spec, lab_spec, lab_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((rows_p, c_p), logits2d.dtype),
        interpret=interpret,
    )(logits2d, lab2d, lse2d, gm2d, nv2d)
    return dx[:rows, :c]
