"""The vocab-chain loss as a registered kernel: fused LM-head +
cross-entropy (Pallas, :mod:`.lm_head_xent`) with the chunked XLA chain
(:mod:`apex_tpu.contrib.xentropy.chunked`) as the declared fallback.

Round-4/5 history, now encoded as dispatch data instead of prose: the
fused kernel measured **0.69x** against XLA's own lowering at
(8192, 50257, 768) fwd+bwd, while the *program-level* chunked chain won
**+13-15%** in-step — so the registered probe defaults every compiled
shape to the chunked XLA path, and only a ledger entry with a measured
win routes a shape to the kernel.  Interpret mode exercises the kernel
(parity coverage); the kernel itself stays tested evidence either way.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from . import dispatch as _dispatch
from .lm_head_xent import _fused_kernel_path


def _vocab_chain_probe(dims):
    # 0.69x at (n=8192, v=50257, e=768): no known win region on
    # compiled TPU — default XLA everywhere until the ledger says
    # otherwise (docs/kernels.md carries the receipts)
    return None, False


def _audit_programs():
    import jax
    sds = jax.ShapeDtypeStruct
    hidden = sds((32, 16), jnp.float32)
    table = sds((40, 16), jnp.float32)
    labels = sds((32,), jnp.int32)

    def _xla(x, w, lab):
        from ..contrib.xentropy.chunked import chunked_lm_head_loss
        return chunked_lm_head_loss(x, w, lab)

    return [("pallas", _fused_kernel_path, (hidden, table, labels)),
            ("xla", _xla, (hidden, table, labels))]


_dispatch.register_kernel(
    "vocab_chain_loss",
    xla_fallback="apex_tpu.contrib.xentropy.chunked.chunked_lm_head_loss",
    threshold_probe=_vocab_chain_probe,
    doc="Fused LM-head + cross-entropy (online-softmax over vocab blocks)",
    audit_programs=_audit_programs)


def vocab_chain_loss(hidden, head_weight, labels, smoothing=0.0,
                     padding_idx=-100, logical_vocab=None,
                     chunk_rows=None):
    """Per-row LM-head cross-entropy, dispatch-gated between the fused
    Pallas kernel and the chunked XLA chain.

    Same contract as :func:`chunked_lm_head_loss` (returns f32 per-row
    losses with ``hidden``'s leading shape).  The kernel arm covers the
    plain-CE case only — smoothing or a lane-padded logical vocab
    always takes the chunked path, which handles both exactly.
    """
    # lazy: contrib.xentropy.chunked imports kernels.dispatch at module
    # top, so a module-level import here would close an import cycle
    from ..contrib.xentropy.chunked import chunked_lm_head_loss

    e = hidden.shape[-1]
    lead = hidden.shape[:-1]
    v = head_weight.shape[0]
    n = math.prod(lead)

    plain = isinstance(smoothing, (int, float)) and smoothing == 0.0
    kernel_eligible = plain and (logical_vocab is None
                                 or logical_vocab >= v)
    if kernel_eligible:
        fp = _dispatch.vocab_chain_fp(n, v, e, hidden.dtype)
        d = _dispatch.decide("vocab_chain_loss", fp)
        if d.tier == "pallas":
            x2d = hidden.reshape(n, e)
            lab = labels.reshape(n).astype(jnp.int32)
            per = _fused_kernel_path(x2d, head_weight, lab)
            # padding rows contribute zero loss AND zero gradient —
            # the where's cotangent to the kernel branch is zero there
            per = jnp.where(lab == padding_idx, jnp.zeros_like(per), per)
            return per.reshape(lead)
    return chunked_lm_head_loss(hidden, head_weight, labels,
                                smoothing=smoothing,
                                padding_idx=padding_idx,
                                logical_vocab=logical_vocab,
                                chunk_rows=chunk_rows)
