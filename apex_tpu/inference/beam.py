"""Beam-search decoding over the LM families' cache protocol.

The deterministic third leg of the decode stack next to
greedy/sampled ``models.gpt.generate`` and
``inference.speculative_generate``: keep the ``num_beams`` highest
cumulative-log-prob continuations per batch item, expanding all beams
in one batched cache pass per step.  (The reference is training-side
only, SURVEY.md §2 — the decode stack has no reference counterpart;
the algorithm is the standard fixed-width beam search.)

TPU shape: beams fold into the batch dimension (caches and token
buffers are ``(B*K, ...)``), every step is one ``decode_step`` + one
``top_k`` over ``K*V`` candidates per item, and the per-step beam
reordering is a gather on the batch-beam axis — all static shapes
inside one ``lax.scan``, compiled once per config (the
``compiled_run_cache`` convention).

Scores carry the raw sum of token log-probs; ranking (and the final
beam choice) optionally normalizes by the GNMT length penalty
(``length_penalty=alpha``).  With ``eos_id`` set, a finished beam
freezes its score and length and pads with ``eos_id`` while
continuing to compete for the final ranking.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def beam_generate(model, prompt_ids, max_new_tokens, num_beams,
                  eos_id=None, length_penalty=0.0, cache_dtype=None,
                  mesh=None):
    """Beam-search continuation of ``prompt_ids (B, P)``: returns the
    best beam per item, ``(B, P + max_new_tokens)`` int32.

    ``num_beams=1`` reduces exactly to greedy ``generate``.
    ``length_penalty`` is the GNMT normalization exponent: candidates
    rank by ``score / ((5 + len) / 6) ** alpha`` (``len`` counts
    generated tokens, frozen at eos), countering beam search's
    short-sequence bias; ``0.0`` (default) ranks by the raw summed
    log-probs.  Raw scores are carried either way — only the ranking
    (and the final beam choice) normalizes.
    ``cache_dtype`` follows generate's contract (``"int8"`` for the
    quantized KV cache).  Sharded decode follows generate's mesh
    convention: a model built with ``tp_axis``/``moe_axis``/``sp_axis``
    passes ``mesh`` and the whole search runs inside ``shard_map``
    (replicated tokens; the beam bookkeeping is identical on every
    device, so the emitted beams are too).
    """
    from ..models.gpt import _check_decode_mesh, _sharded_decode_axes
    from ..nn.modules import Ctx
    from ..utils.jit_cache import compiled_run_cache

    b, p = prompt_ids.shape
    k = int(num_beams)
    if k < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    s_total = p + max_new_tokens
    if s_total > model.max_positions:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_positions {model.max_positions}")
    missing = [a for a in ("init_caches", "prefill", "decode_step")
               if not hasattr(model, a)]
    if missing:
        raise ValueError(
            f"beam_generate needs model.{missing[0]} (the GPT/Llama "
            f"cache protocol)")
    # logical vocab: a pad_vocab_multiple model's table is wider than
    # its emittable id range (pad logits are -1e30)
    vocab = getattr(model, 'vocab_size', None) \
        or model.tok_emb.weight.shape[0]
    if k > vocab:
        raise ValueError(f"num_beams ({k}) exceeds vocab ({vocab})")
    if eos_id is not None and not 0 <= eos_id < vocab:
        raise ValueError(f"eos_id {eos_id} out of vocab range {vocab}")
    guard = getattr(model, "_decode_guard", None)
    if guard is not None:
        guard("beam_generate")
    _check_decode_mesh(model, mesh, what="beam_generate")
    if mesh is not None and not _sharded_decode_axes(model):
        raise ValueError(
            "mesh was passed but the model has no tp_axis/moe_axis/"
            "sp_axis — single-shard decode needs no mesh")

    params = list(model.parameters())
    buffers = list(model.buffers())
    vals = [q.data for q in params] + [bu.data for bu in buffers]
    if cache_dtype is None:
        cache_dtype = model.tok_emb.weight.data.dtype
    if length_penalty < 0.0:
        raise ValueError(
            f"length_penalty must be >= 0, got {length_penalty}")
    alpha = float(length_penalty)
    NEG = jnp.float32(-1e30)

    def _lp(lens):
        # GNMT normalizer; alpha == 0 -> exactly 1.0 (raw ranking)
        return ((5.0 + lens.astype(jnp.float32)) / 6.0) ** alpha

    def run(vals, prompt):
        env = {id(o): v for o, v in zip(params + buffers, vals)}
        ctx = Ctx(env=env, stats_out={}, training=False)
        # prefill ONCE at batch B (the FLOP-dominant phase for long
        # prompts), then fan the caches out item-major to (B*K, ...) —
        # beams of item i occupy rows i*k..i*k+k-1, the layout every
        # later gather assumes
        caches = model.init_caches(b, s_total, dtype=cache_dtype)
        logits, caches = model.prefill(ctx, prompt, caches)
        caches = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, k, axis=0), caches)
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32))        # (B, V)
        scores, tok = jax.lax.top_k(logp, k)          # (B, K) twice
        alive = (tok != eos_id) if eos_id is not None \
            else jnp.ones((b, k), bool)
        lens = jnp.ones((b, k), jnp.int32)            # generated tokens
        buf = jnp.zeros((b, k, max_new_tokens), jnp.int32)
        buf = buf.at[:, :, 0].set(tok)

        def step(carry, t):
            tok, scores, alive, lens, buf, caches = carry
            logits, caches = model.decode_step(
                ctx, tok.reshape(b * k), caches, t)
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32)).reshape(b, k, vocab)
            if eos_id is not None:
                # finished beams: only continuation is eos at +0, so
                # the frozen score keeps competing in the rankings
                frozen = jnp.full((vocab,), NEG).at[eos_id].set(0.0)
                logp = jnp.where(alive[:, :, None], logp,
                                 frozen[None, None, :])
            cand = (scores[:, :, None] + logp).reshape(b, k * vocab)
            # rank by the length-normalized score (alive candidates are
            # one token longer; frozen ones keep their final length),
            # CARRY the raw sum either way
            # per-candidate length: alive beams grow by one token
            denom = _lp(lens + alive.astype(jnp.int32))
            rank = (cand.reshape(b, k, vocab)
                    / denom[:, :, None]).reshape(b, k * vocab)
            _, idx = jax.lax.top_k(rank, k)           # (B, K)
            scores = jnp.take_along_axis(cand, idx, axis=1)
            beam = idx // vocab                       # source beam
            tok = (idx % vocab).astype(jnp.int32)
            rows = (jnp.arange(b)[:, None] * k + beam).reshape(-1)
            caches = jax.tree_util.tree_map(
                lambda c: jnp.take(c, rows, axis=0), caches)
            buf = jnp.take_along_axis(buf, beam[:, :, None], axis=1)
            buf = jax.lax.dynamic_update_slice(
                buf, tok[:, :, None], (0, 0, t - p + 1))
            src_alive = jnp.take_along_axis(alive, beam, axis=1)
            lens = jnp.take_along_axis(lens, beam, axis=1) \
                + src_alive.astype(jnp.int32)
            alive = src_alive
            if eos_id is not None:
                alive = alive & (tok != eos_id)
            return (tok, scores, alive, lens, buf, caches), ()

        if max_new_tokens > 1:
            (tok, scores, alive, lens, buf, caches), _ = jax.lax.scan(
                step, (tok, scores, alive, lens, buf, caches),
                jnp.arange(p, s_total - 1))
        best = jnp.argmax(scores / _lp(lens), axis=1)  # (B,)
        seq = jnp.take_along_axis(
            buf, best[:, None, None], axis=1)[:, 0]   # (B, T)
        return jnp.concatenate([prompt, seq], axis=1)

    def build():
        if mesh is not None:
            from jax.sharding import PartitionSpec as _P
            from ..compat import shard_map as _shard_map
            return jax.jit(_shard_map(
                run, mesh=mesh, in_specs=(_P(), _P()), out_specs=_P(),
                check_vma=False))
        return jax.jit(run)

    fn = compiled_run_cache(
        model, "_beam_jit_cache",
        (b, p, max_new_tokens, k, eos_id, alpha,
         cache_dtype if isinstance(cache_dtype, str)
         else jnp.dtype(cache_dtype).name, mesh),
        params + buffers, build)
    return fn(vals, prompt_ids)
