"""Weight-only int8 quantization for inference.

TPU decode is HBM-bandwidth-bound: each generated token re-reads every
weight, so halving weight bytes (bf16 -> int8) is a direct lever on
tokens/sec (v5e HBM ~819 GB/s; a 124M-param model at bf16 reads ~250MB
per token).  This is weight-ONLY quantization (w8a16): weights live in
HBM as int8 with one fp scale per output row and are dequantized at the
point of use — XLA fuses the ``int8 -> compute-dtype multiply`` into the
consuming matmul, so the full-precision weight tensor never
materializes in HBM.  Compute stays bf16/f32 on the MXU; there is no
activation quantization and no calibration step (absmax per row is
exact for weights).

The reference has no inference path at all (it is a training-side
library; SURVEY.md §2) — this extends the framework's own decode story
(models/gpt.py:generate).

Usage::

    model = llama_from_hf(hf)           # or any family
    quantize_int8(model)                # in place; model is now eval-only
    out = generate(model, prompt, 128)  # decode reads int8 weights

Mechanism: each selected ``Parameter.data`` is replaced by a
:class:`QuantTensor` — a pytree of ``(int8 values, per-row scales)``
that ``Ctx.value`` dequantizes on access inside the jitted program.
Quantized models are inference-only: the train-step builders coerce
``p.data`` through ``jnp.array`` and fail loudly on a QuantTensor.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantTensor(NamedTuple):
    """Int8 weight + per-leading-row scale; dequantizes to
    ``scale.dtype``.  A NamedTuple of arrays, so it traverses jit/pytree
    boundaries like any array container."""
    q: jax.Array          # int8, the original shape
    scale: jax.Array      # (rows, 1, ..., 1) broadcast shape, fp

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.scale.dtype

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def size(self):
        return self.q.size

    def dequant(self):
        return self.q.astype(self.scale.dtype) * self.scale


def _absmax_int8(xf, axis, scale_dtype):
    """The symmetric-absmax int8 core shared by weight and KV-cache
    quantization: ``xf`` fp32, reduce over ``axis``.  The scale is cast
    to ``scale_dtype`` BEFORE rounding — quantization and
    dequantization must use the identical stored scale value, or the
    round-trip error bound silently grows by the cast's rounding."""
    absmax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = (jnp.maximum(absmax, 1e-12) / 127.0).astype(scale_dtype)
    q = jnp.clip(jnp.round(xf / scale.astype(jnp.float32)),
                 -127, 127).astype(jnp.int8)
    return q, scale


#: public name for the shared absmax core — the serve engine's paged
#: pool quantizes per-position writes through the SAME function the
#: contiguous QuantKV cache uses, so a block-pooled int8 cache stores
#: byte-identical values to the private-buffer one
absmax_int8 = _absmax_int8


def quantize_tensor_int8(x, dtype=None):
    """Absmax-per-row symmetric int8: ``x (rows, ...)`` -> QuantTensor
    with one scale per leading row (for a torch-layout ``(out, in)``
    Linear weight that is per-output-channel; for an embedding, per
    vocab row).  ``dtype``: dequantization dtype (default: x's)."""
    x = jnp.asarray(x)
    if x.ndim < 2:
        raise ValueError(
            f"quantize_tensor_int8 expects a >=2-D weight, got shape "
            f"{x.shape} — 1-D params (norms/biases) stay full precision")
    q, scale = _absmax_int8(x.astype(jnp.float32),
                            tuple(range(1, x.ndim)), dtype or x.dtype)
    return QuantTensor(q, scale)


def quantize_int8(model, min_size=4096, dtype=None):
    """Quantize a model's weight matrices to int8 in place, for decode.

    Every parameter with ``ndim >= 2`` and at least ``min_size`` elements
    is replaced (Linear/projection weights, embeddings); 1-D params
    (norm scales, biases) and small tensors stay full precision — their
    bytes are noise and their dynamic range matters.  Reparameterization
    *source* parameters (WeightNorm's ``_g``/``_v``, LoRA's
    ``_w0``/``_lora_a``/``_lora_b``) are skipped too: they feed a derived
    weight whose closure expects full-precision sources, and quantizing a
    trainable rank factor is never what the caller meant — merge first
    (``remove_reparameterization``) to quantize the composed weight.
    Returns the model (now in ``eval()`` mode).  The change is
    inference-only: building a train step over a quantized model raises.
    ``dtype`` sets the dequantization dtype (default: each weight's own;
    pass ``jnp.bfloat16`` to also cast compute).
    """
    # identity set of reparameterization sources: exact (registry-driven),
    # not a name-suffix heuristic
    reparam_sources = set()
    for m in model.modules():
        for fn in (getattr(m, "_reparameterizations", None) or {}).values():
            reparam_sources.update(id(p) for p in fn.get_params(m))
    n = 0
    for p in model.parameters():
        if p is None or getattr(p, "_derived", None) is not None \
                or id(p) in reparam_sources:
            continue
        d = p.data
        if isinstance(d, QuantTensor):
            continue
        if d.ndim >= 2 and d.size >= min_size:
            p.data = quantize_tensor_int8(d, dtype=dtype)
            n += 1
    if n == 0:
        raise ValueError(
            f"quantize_int8: no parameter met the criteria (ndim >= 2, "
            f"size >= {min_size}) — nothing was quantized")
    model.eval()
    return model


def gather_rows(ctx, param, ids):
    """Embedding-style row gather that stays int8 until after the
    gather: ``table[ids]`` reads only the selected rows' int8 bytes
    (plus their scales) instead of dequantizing the whole table first —
    at GPT-2's vocab the full-table dequant is ~75 MB of bf16 writes
    per decode step.  Falls back to ``ctx.value(param)[ids]`` for
    unquantized (or derived) parameters."""
    v = ctx.raw(param)
    if isinstance(v, QuantTensor):
        rows = v.q[ids].astype(v.scale.dtype)
        return rows * v.scale[ids]
    return v[ids]


# ---------------------------------------------------------------- KV cache


class QuantKV(NamedTuple):
    """Int8 KV cache: values ``(B, H, S, D)`` int8 with one fp scale per
    cached position ``(B, H, S, 1)``.  Decode at long context is
    cache-traffic-bound — every step re-reads the whole cache — so int8
    halves that traffic the way weight-only int8 halves weight reads.
    Quantization is per-position absmax (exact at write time: each
    position is written once and never rewritten), so the error bound
    matches :func:`quantize_tensor_int8`'s per-row bound.  A NamedTuple
    of arrays: traverses jit/scan/shard_map like any pytree."""
    q: jax.Array          # int8 (B, H, S, D)
    scale: jax.Array      # fp  (B, H, S, 1)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.scale.dtype


def make_kv_cache(shape, dtype):
    """Zeros cache of ``shape (B, H, S, D)``: a plain array for a fp
    ``dtype``, a :class:`QuantKV` for int8 — either the string
    ``"int8"`` or ``jnp.int8``, normalized so both spellings build the
    quantized cache (a RAW int8 cache would truncating-cast float K/V
    to garbage; there is no sane meaning for it).  Scales are fp32 —
    1/D of the int8 bytes, negligible traffic."""
    if jnp.dtype(dtype) == jnp.dtype("int8"):
        return QuantKV(jnp.zeros(shape, jnp.int8),
                       jnp.zeros(shape[:-1] + (1,), jnp.float32))
    return jnp.zeros(shape, dtype)


def kv_write(cache, new, start):
    """Write ``new (..., S_c, D)`` into the cache at index tuple
    ``start`` (4-d).  Plain caches cast-and-update; QuantKV quantizes
    each written position against its own absmax."""
    if isinstance(cache, QuantKV):
        q, scale = _absmax_int8(new.astype(jnp.float32), -1,
                                cache.scale.dtype)
        return QuantKV(
            jax.lax.dynamic_update_slice(cache.q, q, start),
            jax.lax.dynamic_update_slice(cache.scale, scale, start))
    return jax.lax.dynamic_update_slice(cache, new.astype(cache.dtype),
                                        start)


def kv_value(cache, dtype=jnp.float32):
    """Read the cache as ``dtype`` (QuantKV dequantizes; XLA fuses the
    int8→fp multiply into the consuming attention matmul)."""
    if isinstance(cache, QuantKV):
        return cache.q.astype(dtype) * cache.scale.astype(dtype)
    return cache.astype(dtype)
