"""Stateful multi-turn decode sessions over the LM cache protocol.

``models.gpt.generate`` is one-shot: prompt in, tokens out, caches
gone.  The chat/serving pattern — prefill a history once, generate,
append the next user turn, generate again — would re-prefill the whole
conversation every turn.  :class:`DecodeSession` keeps the KV caches
(and the write cursor) alive across calls instead: ``append`` ingests
tokens at the cursor, ``generate`` continues from it, and every turn
reuses the same compiled programs (the cursor is a traced argument, so
shapes and sampling config — not positions — key the compilation,
through the shared ``compiled_run_cache`` with its parameter-identity
and LRU invariants: a LoRA apply/merge mid-session recompiles against
the new parameter objects rather than silently decoding stale
weights).

The reference has no inference path (SURVEY.md §2 — training-side
library); this is the serving-session layer over the decode stack, and
it composes with everything the underlying paths do: int8 KV caches,
int8 weights, and the rolling sliding-window cache.  Sharded decode
(tp/sp/moe) stays with the one-shot ``generate(mesh=...)`` drivers —
a session would have to hold device-sharded caches across shard_map
regions; refused loudly for now.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class DecodeSession:
    """Incremental decoding with persistent KV caches.

    ``DecodeSession(model, batch=1, capacity=None, cache_dtype=None)``
    allocates caches for ``capacity`` positions (default
    ``model.max_positions``).  Then, any interleaving of:

    - ``append(tokens)`` — teacher-force ``tokens (B, S)`` into the
      caches (a user turn, a system prompt); returns the logits for
      the ingested positions.
    - ``generate(n, temperature=0.0, top_k=None, top_p=None, key=None)``
      — continue from the cursor, returning the ``(B, n)`` new tokens
      (they are also ingested, like a model turn).
    - ``reset()`` — drop the decode state, keep the session.

    ``session.position`` is the write cursor.  Output equals one-shot
    ``generate`` on the concatenated history (cache-mediated numerics:
    ingest runs through ``decode_chunk``).
    """

    def __init__(self, model, batch=1, capacity=None, cache_dtype=None):
        from ..models.gpt import _sharded_decode_axes

        for a in ("init_caches", "decode_chunk", "decode_step"):
            if not hasattr(model, a):
                raise ValueError(
                    f"DecodeSession needs model.{a} (the GPT/Llama "
                    f"cache protocol)")
        guard = getattr(model, "_decode_guard", None)
        if guard is not None:
            guard("DecodeSession")
        if _sharded_decode_axes(model):
            raise NotImplementedError(
                "DecodeSession holds caches across calls and runs "
                "single-shard; sharded models (tp/sp/moe) decode "
                "through the one-shot generate(mesh=...) drivers")
        self.model = model
        self.batch = batch
        self.capacity = capacity if capacity is not None \
            else model.max_positions
        if not 1 <= self.capacity <= model.max_positions:
            raise ValueError(
                f"capacity must be in [1, max_positions="
                f"{model.max_positions}], got {self.capacity}")
        self._cache_dtype = cache_dtype if cache_dtype is not None \
            else model.tok_emb.weight.data.dtype
        self._vocab = getattr(model, 'vocab_size', None) \
            or model.tok_emb.weight.shape[0]
        self.reset()

    def reset(self):
        self.caches = self.model.init_caches(
            self.batch, self.capacity, dtype=self._cache_dtype)
        self.position = 0
        self._last_logits = None

    # -- internals ---------------------------------------------------------

    def _compiled(self, cfg, build_with_params):
        """A compiled program from the model's shared session cache:
        ``build_with_params(params)`` closes over the CURRENT
        Parameter/Buffer objects, and the cache keys on their ids
        (utils/jit_cache.py invariants — LoRA swaps miss, entries
        LRU-capped), so stale zips cannot read wrong weights."""
        from ..utils.jit_cache import compiled_run_cache

        params = list(self.model.parameters()) + \
            list(self.model.buffers())
        fn = compiled_run_cache(
            self.model, "_session_jit_cache", cfg, params,
            lambda: build_with_params(params))
        return fn, [p.data for p in params]

    def _check_room(self, n, what):
        if self.position + n > self.capacity:
            raise ValueError(
                f"{what}: cursor {self.position} + {n} tokens exceeds "
                f"the session capacity {self.capacity} — reset() or "
                f"allocate a larger session")

    @staticmethod
    def _ctx(params, vals):
        from ..nn.modules import Ctx
        return Ctx(env={id(p): v for p, v in zip(params, vals)},
                   stats_out={}, training=False)

    # -- public ------------------------------------------------------------

    def append(self, tokens):
        """Ingest ``tokens (B, S)`` at the cursor; returns their logits
        ``(B, S, V)`` (the last row is the next-token distribution)."""
        tokens = jnp.asarray(tokens)
        if tokens.ndim != 2 or tokens.shape[0] != self.batch:
            raise ValueError(
                f"append expects (batch={self.batch}, S) token ids, "
                f"got {tokens.shape}")
        s = int(tokens.shape[1])
        self._check_room(s, "append")

        def build(params):
            def run(vals, toks, caches, pos):
                ctx = self._ctx(params, vals)
                return self.model.decode_chunk(ctx, toks, caches, pos)
            return jax.jit(run)

        cache_name = self._cache_dtype if isinstance(
            self._cache_dtype, str) else jnp.dtype(self._cache_dtype).name
        fn, vals = self._compiled(
            ("session-append", self.batch, s, cache_name), build)
        logits, self.caches = fn(vals, tokens, self.caches,
                                 jnp.int32(self.position))
        self.position += s
        self._last_logits = logits[:, -1]
        return logits

    def generate(self, max_new_tokens, temperature=0.0, top_k=None,
                 top_p=None, key=None):
        """Continue the session by ``max_new_tokens`` (greedy, or
        sampled with generate()'s knobs); the emitted tokens are
        ingested like any turn.  Requires at least one prior ``append``
        (there is nothing to continue otherwise)."""
        from ..models.gpt import make_sampler

        if self.position == 0:
            raise ValueError(
                "generate on an empty session — append a prompt first")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self._check_room(max_new_tokens, "generate")
        sample = make_sampler(temperature, top_k, top_p, self._vocab)
        if temperature > 0.0 and key is None:
            raise ValueError("sampling (temperature > 0) needs a PRNG "
                             "key")
        if key is None:
            key = jax.random.PRNGKey(0)

        def build(params):
            def run(vals, caches, pos, last_logits, key):
                ctx = self._ctx(params, vals)
                key, sub = jax.random.split(key)
                tok = sample(last_logits, sub)    # token AT the cursor

                def step(carry, t):
                    tok, caches, key, _ = carry
                    logits, caches = self.model.decode_step(
                        ctx, tok, caches, t)
                    key, sub = jax.random.split(key)
                    nxt = sample(logits, sub)
                    return (nxt, caches, key, logits), tok

                (_, caches, _, logits), toks = jax.lax.scan(
                    step, (tok, caches, key, last_logits),
                    pos + jnp.arange(max_new_tokens, dtype=jnp.int32))
                # toks = the n EMITTED tokens (each step emits the
                # token it consumed); the final carry logits are the
                # cursor's next-token distribution, kept so a
                # back-to-back generate() continues correctly
                return jnp.swapaxes(toks, 0, 1), logits, caches
            return jax.jit(run)

        cache_name = self._cache_dtype if isinstance(
            self._cache_dtype, str) else jnp.dtype(self._cache_dtype).name
        fn, vals = self._compiled(
            ("session-generate", self.batch, max_new_tokens,
             float(temperature), top_k,
             None if top_p is None else float(top_p), cache_name), build)
        toks, self._last_logits, self.caches = fn(
            vals, self.caches, jnp.int32(self.position),
            self._last_logits, key)
        self.position += max_new_tokens
        return toks


class PagedSession:
    """A decode session whose KV state is a BLOCK TABLE into a shared
    :class:`~apex_tpu.serve.ServeEngine` pool — no private cache
    buffer.

    Where :class:`DecodeSession` allocates ``(B, H, capacity, D)``
    caches per layer up front (capacity paid even for a two-turn
    chat), a PagedSession holds only the integer ids of the pool
    blocks its history actually fills, growing block-by-block as the
    conversation does; hundreds of sessions share the engine's one
    preallocated buffer.  The compiled programs are the ENGINE's
    prefill/decode programs — the same executables its continuous-
    batching loop dispatches — so an interactive session and the
    batch-serving path cannot drift numerically, and opening a session
    compiles nothing new after the engine has warmed its buckets.

    Same surface as DecodeSession (``append`` / ``generate`` /
    ``reset`` / ``position``), batch 1, greedy-only ``generate``
    (the serve programs sample in-program; sampled decode stays on
    DecodeSession, the single-session compatibility path).  ``append``
    returns only the LAST position's logits ``(1, V)`` — the paged
    prefill never materializes per-position logits for the whole
    chunk.  Use as a context manager (or call ``close()``) so the
    blocks return to the pool.
    """

    def __init__(self, engine):
        self.engine = engine
        self._table = []
        self.position = 0
        self._last_logits = None
        # prefix-cache state: every ingested token in order (the chain
        # source), the rolling chain keys of committed full blocks, and
        # the tag the chain was built under — a weight republish
        # mid-session changes the engine's tag and stops this session
        # from publishing further (mixed-epoch) blocks
        self._tokens = []
        self._chain = []
        self._committed = 0
        self._cache_tag = None
        self._cacheable = True

    # -- block-table state -------------------------------------------------

    @property
    def block_table(self):
        """The session's logical→physical block ids (read-only view)."""
        return tuple(self._table)

    def _ensure(self, n_positions, what):
        from ..serve.pool import blocks_for
        eng = self.engine
        if n_positions > eng.model.max_positions:
            raise ValueError(
                f"{what}: {n_positions} positions exceed max_positions "
                f"{eng.model.max_positions}")
        need = blocks_for(n_positions, eng.block_size) - len(self._table)
        if need > 0:
            ids = eng.block_pool.alloc(need)
            if ids is None:
                raise RuntimeError(
                    f"{what}: block pool exhausted "
                    f"({eng.block_pool.in_use}/{eng.block_pool.capacity}"
                    f" in use) — close idle sessions or build the "
                    f"engine with more num_blocks")
            self._table.extend(ids)

    def _commit_full(self):
        """Publish every newly full block into the engine pool's hash
        index (rolling chain over the session's ingested tokens) —
        the PagedSession half of the serve scheduler's note_commit."""
        from ..serve.pool import chain_key
        eng = self.engine
        sched = eng.scheduler
        if not sched.prefix_cache or not self._cacheable:
            return
        if self._cache_tag is None:
            self._cache_tag = sched.cache_tag
        elif sched.cache_tag != self._cache_tag:
            # publish_weights re-tagged the engine mid-session: rows
            # already written used the old weights, so nothing this
            # session writes from here on may enter the index
            self._cacheable = False
            return
        bs = eng.block_size
        full = min(self.position // bs, len(self._table),
                   len(self._tokens) // bs)
        while self._committed < full:
            i = self._committed
            prev = self._chain[i - 1] if i else ""
            key = chain_key(prev, self._tokens[i * bs:(i + 1) * bs],
                            self._cache_tag)
            self._chain.append(key)
            eng.block_pool.commit(self._table[i], key)
            self._committed = i + 1

    def _adopt_prefix(self, toks) -> int:
        """First-append prefix walk: adopt every cached full block of
        ``toks`` shared and return the number of already-ingested
        positions.  A FULL-chain hit forks the last shared block
        copy-on-write (the final token must re-ingest for its logits,
        and that row lands inside the shared block)."""
        import numpy as np
        from ..serve.pool import chain_keys
        from ..runtime import executor as _executor
        from ..observe import registry as _obs
        eng = self.engine
        sched = eng.scheduler
        if not sched.prefix_cache:
            return 0
        tag = sched.cache_tag
        keys = chain_keys(toks, eng.block_size, tag)
        shared = eng.block_pool.acquire_prefix(keys)
        if not shared:
            return 0
        self._cache_tag = tag
        if len(shared) * eng.block_size >= toks.size:
            # full hit — fork the last shared block so the re-ingested
            # final token writes an exclusive copy
            fdst_l = eng.block_pool.alloc(1)
            if fdst_l is None:
                # no room for the fork: fall back to a partial hit by
                # releasing the last shared block (it retires cached)
                eng.block_pool.free([shared[-1]])
                shared = shared[:-1]
            else:
                fsrc, fdst = shared[-1], fdst_l[0]
                prog = eng._copy_program()
                eng.pool = _executor.executor.submit(
                    prog, (eng.pool, np.int32(fsrc), np.int32(fdst)),
                    step=next(eng._dispatch_no))
                eng.block_pool.free([fsrc])   # copy is in the stream
                eng._cow_forks += 1
                _obs.counter("serve.prefix.cow_forks").inc()
                self._table = shared[:-1] + [fdst]
                self._chain = keys[:len(shared) - 1]
                self._committed = len(shared) - 1
                self.position = toks.size - 1
                return self.position
        self._table = list(shared)
        self._chain = keys[:len(shared)]
        self._committed = len(shared)
        self.position = len(shared) * eng.block_size
        return self.position

    # -- public ------------------------------------------------------------

    def append(self, tokens):
        """Ingest ``tokens`` (a 1-D sequence, or ``(1, S)``) at the
        cursor through the engine's chunked prefill program; returns
        the final ingested position's logits ``(1, V)``."""
        from ..serve.scheduler import bucket
        import numpy as np
        eng = self.engine
        toks = np.asarray(tokens, np.int32).reshape(-1)
        if toks.size == 0:
            raise ValueError("append of zero tokens")
        prefill_prog, _ = eng._programs()
        chunk = eng.scheduler.prefill_chunk
        done = 0
        if self.position == 0:
            # empty session: a conversation replay (or a shared system
            # prompt another session committed) is a natural prefix hit
            done = self._adopt_prefix(toks)
        self._tokens.extend(int(t) for t in toks)
        while done < toks.size:
            n = int(min(chunk, toks.size - done))
            self._ensure(self.position + n, "append")
            nb = bucket(len(self._table))
            padded = np.zeros((1, chunk), np.int32)
            padded[0, :n] = toks[done:done + n]
            table = np.asarray(
                [self._table + [0] * (nb - len(self._table))], np.int32)
            from ..runtime import executor as _executor
            last, eng.pool = _executor.executor.submit(
                prefill_prog,
                (eng._vals(), eng.pool, padded, table,
                 np.int32(self.position), np.int32(n)),
                step=next(eng._dispatch_no))
            self.position += n
            done += n
            self._commit_full()
        self._last_logits = last
        return last

    def generate(self, max_new_tokens):
        """Greedily continue by ``max_new_tokens`` (emitted tokens are
        ingested, like a model turn); returns ``(1, n)`` token ids."""
        from ..serve.scheduler import bucket
        import numpy as np
        eng = self.engine
        if self.position == 0:
            raise ValueError(
                "generate on an empty session — append a prompt first")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        _, decode_prog = eng._programs()
        from ..runtime import executor as _executor
        tok = int(jnp.argmax(self._last_logits[0]))
        out = [tok]
        for i in range(max_new_tokens):
            # ingest the token at the cursor; the final iteration only
            # refreshes _last_logits (its sampled successor is the
            # NEXT generate's first token)
            self._ensure(self.position + 1, "generate")
            nb = bucket(len(self._table))
            table = np.asarray(
                [self._table + [0] * (nb - len(self._table))], np.int32)
            nxt, logits, eng.pool = _executor.executor.submit(
                decode_prog,
                (eng._vals(), eng.pool,
                 np.asarray([out[-1]], np.int32),
                 np.asarray([self.position], np.int32), table),
                step=next(eng._dispatch_no))
            self.position += 1
            self._tokens.append(out[-1])
            self._commit_full()
            self._last_logits = logits
            if i < max_new_tokens - 1:
                out.append(int(np.asarray(nxt)[0]))
        return jnp.asarray([out], jnp.int32)

    def reset(self):
        """Drop the decode state and return the blocks to the pool;
        the session object stays usable."""
        if self._table:
            self.engine.block_pool.free(self._table)
        self._table = []
        self.position = 0
        self._last_logits = None
        self._tokens = []
        self._chain = []
        self._committed = 0
        self._cache_tag = None
        self._cacheable = True

    close = reset

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.reset()
