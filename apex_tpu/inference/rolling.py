"""Rolling (modular) KV cache for sliding-window decode.

A ``sliding_window=w`` model (models/llama.py — the Mistral band) can
only ever attend the last ``w`` positions, so its decode cache needs
exactly ``w`` slots: position ``p`` lives in slot ``p % w`` and new
writes overwrite the positions that just fell out of the band.  Cache
HBM per layer drops from O(context) to O(window) — at long context the
cache is decode's dominant memory AND traffic term, so this is the
Mistral-serving memory lever the band itself promises.  (The reference
is training-side only, SURVEY.md §2; the rolling buffer is the standard
serving companion of banded attention.)

No slot-position bookkeeping arrays are needed: the decode protocol
writes positions contiguously (prefill chunks, then one position per
step), so after everything below ``t_hi`` is written, slot ``s`` holds
global position ``t_hi-1 - ((t_hi-1 - s) mod W)`` — a closed form
(:func:`rolling_slot_positions`), negative iff the slot was never
written.  The attention mask derives validity entirely from it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: Extra slots past the window in every rolling cache.  Speculative
#: decoding REWINDS after rejected proposals; a rejected chunk's write
#: lands in slots that, with exactly ``window`` slots, would clobber
#: live band keys (slot collisions mod W destroy positions the
#: post-rewind queries still need).  With ``window + SLACK`` slots a
#: stale write of length <= SLACK aliases — under the closed-form
#: position mask — to a position at least one full window behind every
#: later query, so the band mask provably excludes it, and the
#: contiguous re-writes after the rewind reclaim the slots.  Bounds the
#: verification chunk: speculative k+1 <= SLACK (checked there).
ROLLING_SLACK = 32


def rolling_slot_positions(n_slots, t_hi):
    """Global position held by each of the ``n_slots`` cache slots once
    positions ``0 .. t_hi-1`` have been written (``t_hi`` may be
    traced).  Slot ``s`` holds the LARGEST ``p < t_hi`` with
    ``p % n_slots == s``; negative means never written."""
    last = t_hi - 1
    s = jnp.arange(n_slots, dtype=jnp.int32)
    return last - jnp.mod(last - s, n_slots)


def window_retired_blocks(t_hi, window, block_size):
    """Block-table generalization of the rolling cache's eviction
    arithmetic: with positions ``0 .. t_hi-1`` written and a sliding
    window ``w``, every future query sits at position ``>= t_hi - 1``,
    so the earliest key any of them can reach is
    ``t_hi - w`` (band: ``t - w < key <= t``).  A logical block ``b``
    (positions ``[b·bs, (b+1)·bs)``) is *retired* — freeable, its
    physical block returnable to the pool — once its LAST position
    falls below that reach: ``(b+1)·bs - 1 < t_hi - w``.  Returns the
    count of retired leading blocks (host int math; the serve
    scheduler frees exactly that prefix of a windowed session's table
    and nulls the entries, which the band mask already excludes)."""
    if window is None:
        return 0
    return max(0, (int(t_hi) - int(window)) // int(block_size))


def rolling_kv_write(cache, new, t0):
    """Write chunk ``new (B, H, S_c, D)`` at global positions
    ``t0 ..`` into the W-slot rolling cache (slot = position mod W).

    ``S_c == 1`` takes an O(1) single-slot ``dynamic_update_slice``;
    longer chunks (which may wrap) use one full-width masked select —
    O(W) traffic, the same order the attention read already pays.
    Chunks LONGER than the cache keep only their last ``W`` rows (the
    earlier ones are already out of every future query's band).
    QuantKV caches quantize per-position first (inference/quant.py
    values — identical stored bytes to the full-cache write)."""
    from .quant import QuantKV, _absmax_int8

    w, s_c = cache.shape[2], new.shape[2]
    if s_c > w:
        return rolling_kv_write(cache, new[:, :, s_c - w:, :],
                                t0 + (s_c - w))

    def write_arr(arr, src):
        if s_c == 1:
            return jax.lax.dynamic_update_slice(
                arr, src, (0, 0, jnp.mod(t0, w), 0))
        # slot s receives chunk row d = (s - t0) mod W when d < S_c
        d = jnp.mod(jnp.arange(w, dtype=jnp.int32) - t0, w)
        cand = jnp.take(src, jnp.clip(d, 0, s_c - 1), axis=2)
        own = (d < s_c)[None, None, :, None]
        return jnp.where(own, cand, arr)

    if isinstance(cache, QuantKV):
        q, scale = _absmax_int8(new.astype(jnp.float32), -1,
                                cache.scale.dtype)
        return QuantKV(write_arr(cache.q, q),
                       write_arr(cache.scale, scale))
    return write_arr(cache, new.astype(cache.dtype))
