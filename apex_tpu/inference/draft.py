"""Draft models for speculative decoding: construction + distillation.

A draft model's only job is agreeing with the target's greedy argmax —
acceptance rate is the single quality axis (speculative decoding is
exact for ANY draft; see speculative.py).  Two entry points:

* :func:`make_self_draft` — an exact copy of the target.  Acceptance
  is 100% by construction, which makes it the measurement fixture: the
  CPU tier-1 speculative arm pins its >= 2 tokens/tick floor on a
  self-draft trace, isolating the verify machinery's overhead from
  draft quality.  (In production a self-draft is pointless — it costs
  as much as the target — but a QUANTIZED self-draft is not: serve the
  copy int8 weight-only and its decode is cheaper while acceptance
  stays near-perfect.)
* :func:`train_draft` — hard-label distillation of a small draft
  toward the target's own argmax stream, the label the acceptance test
  actually applies.  Runs through the standard fused train step
  (:func:`apex_tpu.training.step.make_train_step` + ``FusedAdam``), so
  draft training inherits the runtime's compile-once discipline.

:func:`make_distill_step` is the persistent core both entry points and
the rollout runtime share: optimizer + fused step built ONCE, then
``dstep(xs)`` labels and steps for as long as the job lives.
``train_draft`` used to rebuild the optimizer per call — fine for a
one-shot offline distill, wrong for *online* distillation where the
draft trains continuously against live acceptance telemetry
(``apex_tpu.rollout.OnlineDistiller``): Adam moments and the compiled
program must survive across publish windows.

``apex_tpu.serve`` consumes drafts only through this module and
:func:`~apex_tpu.inference.speculative.speculative_generate`'s public
surface — the serve engine never reaches into speculative.py
internals.
"""
from __future__ import annotations

import copy

import jax.numpy as jnp
import numpy as np

__all__ = ["make_self_draft", "make_distill_step", "train_draft"]


def make_self_draft(target):
    """An independent deep copy of ``target`` in eval mode — the
    full-acceptance draft (see module docstring for when that is
    useful).  The copy shares nothing with the original: serving it
    from its own (typically int8) KV pool or quantizing its weights
    never touches the target."""
    draft = copy.deepcopy(target)
    draft.eval()
    return draft


class DistillStep:
    """Persistent hard-label distillation step (see
    :func:`make_distill_step`).  ``self.step`` is the underlying fused
    :class:`~apex_tpu.training.step.TrainStep` — its ``state`` is what a
    rollout checkpoint saves so a resumed job keeps the draft's Adam
    moments and loss-scale history (loss-trajectory reproducibility)."""

    def __init__(self, draft, target, *, lr=1e-3):
        from .. import nn as _nn
        from ..optimizers.fused_adam import FusedAdam
        from ..training.step import make_train_step

        target.eval()
        draft.train()
        self.draft = draft
        self.target = target
        self.optimizer = FusedAdam(list(draft.parameters()), lr=lr)
        self.step = make_train_step(
            draft, self.optimizer,
            lambda o, t: _nn.functional.cross_entropy(
                o.reshape((-1, o.shape[-1])), t.reshape((-1,))))
        self.calls = 0

    def __call__(self, xs) -> float:
        """Label ``xs`` (B,S int ids) with the live target's argmax and
        take one fused step on the draft.  The target is read at CALL
        time — when it is a serve engine's hot-swapped model, labels
        track the published weights automatically."""
        xs = jnp.asarray(np.asarray(xs, np.int32))
        labels = np.argmax(
            np.asarray(self.target(xs)), -1).astype(np.int32)
        loss = float(self.step(xs, jnp.asarray(labels)))
        self.calls += 1
        return loss


def make_distill_step(draft, target, *, lr=1e-3) -> DistillStep:
    """Build the persistent distillation step: one ``FusedAdam`` + one
    fused train step over ``draft``, labels from ``target``'s argmax.
    Call the result with ``(B,S)`` id batches for as long as the job
    lives — compile-once, moments persist."""
    return DistillStep(draft, target, lr=lr)


def train_draft(draft, target, tokens, *, steps=50, batch_size=8,
                seq_len=32, lr=1e-3, seed=0):
    """Distill ``draft`` toward ``target``'s greedy labels over a token
    stream.

    ``tokens`` is a flat 1-D id array (any corpus sample); each step
    draws ``batch_size`` random ``seq_len`` windows, labels every
    position with the TARGET's argmax next-token prediction (hard-label
    distillation — exactly the event the acceptance rule tests), and
    takes one fused train step on the draft (one
    :func:`make_distill_step`, built once).  Returns the per-step loss
    list (monitoring only; the metric that matters is the acceptance
    rate the served draft achieves).
    """
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    if tokens.size < seq_len + 1:
        raise ValueError(
            f"train_draft needs at least seq_len+1={seq_len + 1} "
            f"tokens, got {tokens.size}")
    dstep = make_distill_step(draft, target, lr=lr)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(int(steps)):
        starts = rng.integers(0, tokens.size - seq_len, size=batch_size)
        xs = np.stack([tokens[s:s + seq_len] for s in starts])
        losses.append(dstep(xs))
    draft.eval()
    return losses
