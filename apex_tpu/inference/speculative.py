"""Greedy speculative decoding: a small draft model proposes, the target
model verifies — decode latency drops while the OUTPUT IS EXACTLY the
target model's own greedy decode.

Why it works on TPU: single-token decode is HBM-bound (every step
re-reads all weights to produce one token), but scoring ``k+1`` tokens
in one cached forward (``decode_chunk``) costs nearly the same HBM
traffic as scoring one.  So let a cheap draft model propose ``k`` tokens
autoregressively and the expensive target verify them in ONE chunk:
each accepted prefix amortizes the target's weight reads over several
tokens.  With greedy acceptance the guarantee is exact: a draft token is
accepted iff it equals the target's own argmax, so the emitted sequence
matches ``generate(target, temperature=0)`` for ANY draft — the draft
only changes speed, never output
(tests/test_speculative.py::test_output_matches_target_greedy).  The
one caveat is floating point, not logic: the chunked and single-token
paths share one attention body (LlamaBlock.decode delegates to
decode_chunk), but XLA may reduce the two shapes in different orders,
and an exact argmax TIE between top-2 logits can then resolve
differently.  Tests assert bit-identity; bench tolerates a rare tie.

Both models must expose the cache protocol (``init_caches`` /
``decode_step`` / ``decode_chunk`` / ``prefill`` — the GPT and Llama
families both do) and share a vocabulary; target and draft need not be
the same family.  Pair naturally with weight-only int8 on the draft
(quant.py) — the draft's quality only gates the acceptance rate.

Cache-staleness invariant (why rejected tokens need no cleanup): cache
entries are indexed by position and attention masks strictly by
position, so a slot written by a later-rejected token is invisible until
the position is re-fed — and re-feeding overwrites the slot first.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def speculative_generate(target, draft, prompt_ids, max_new_tokens,
                         k=4, cache_dtype=None, temperature=0.0,
                         key=None, mesh=None, return_stats=False):
    """Decode of ``target`` accelerated by ``draft`` proposals.

    ``prompt_ids (B, P)`` -> ``(B, P + max_new_tokens)``.

    ``temperature == 0`` (default): greedy — bit-identical to
    ``generate(target, prompt_ids, max_new_tokens)`` for ANY draft.
    ``k``: draft tokens proposed per verification chunk; each round
    accepts between 1 and ``k + 1`` tokens (the verified draft prefix
    plus the target's own next token), so rounds <= max_new_tokens.
    The batch runs in LOCKSTEP: every round advances all rows by the
    batch-minimum accepted count (the cache protocol takes one position
    for the whole batch).  This is exactly correct — a position re-fed
    next round reproduces the identical greedy token, since emitted
    tokens are always the target's own argmax — it only costs some
    acceptance on rows that agreed further.  Batch 1 pays no such tax.

    ``temperature > 0``: SAMPLED speculative decoding (Leviathan et al.
    rejection scheme; needs ``key``, batch 1 only).  The draft SAMPLES
    each proposal from its own softmax; the target accepts token ``d``
    with probability ``min(1, p_t(d) / p_d(d))`` and, on the first
    rejection, resamples from the normalized residual
    ``max(p_t - p_d, 0)`` — the emitted DISTRIBUTION is exactly the
    target's own sampling at this temperature, for any draft (the
    classic guarantee; tests check the marginal distribution against
    the exactly-enumerated 2-step marginal of a tiny model).  Re-fed positions under
    lockstep would be RE-sampled, which breaks the guarantee for
    batch > 1 — hence the batch-1 restriction.

    Sharded decode: if the target and/or draft was built with
    ``tp_axis`` (head-sharded), ``moe_axis`` (expert-routed), or
    ``sp_axis`` (time-sharded KV cache), pass ``mesh`` (a Mesh carrying
    the axis/axes) — the whole speculative program runs inside
    ``shard_map`` with generate()'s decode convention (replicated
    tokens/key; TP shards caches with psum-replicated logits, MoE
    routes verification chunks through the expert all_to_all, SP
    lse-merges partial attention over its time-sharded cache blocks —
    parallel/context_parallel.py), so the exactness guarantees hold
    unchanged; a model without sharded axes computes replicated inside
    the same region (the usual big-sharded-target /
    small-replicated-draft serving shape).
    """
    from ..nn.modules import Ctx

    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    sampled = temperature > 0.0
    if sampled and key is None:
        raise ValueError("sampled speculative decoding (temperature > 0) "
                         "needs a PRNG key")
    if sampled and prompt_ids.shape[0] != 1:
        raise ValueError(
            "sampled speculative decoding supports batch 1 (lockstep "
            "re-feeding would resample committed tokens; see docstring)")
    if key is None:
        key = jax.random.PRNGKey(0)
    for name, m in (("target", target), ("draft", draft)):
        missing = [a for a in ("init_caches", "decode_step",
                               "decode_chunk", "prefill")
                   if not hasattr(m, a)]
        if missing:
            raise ValueError(
                f"speculative_generate needs {name}.{missing[0]} "
                f"(the GPT/Llama cache protocol: init_caches, "
                f"decode_step, decode_chunk, prefill)")
        from ..models.gpt import _check_decode_mesh, _sharded_decode_axes
        guard = getattr(m, "_decode_guard", None)
        if guard is not None:
            # unsupported compositions (sp x moe) refuse here, not
            # mid-trace — and before any 'pass mesh=' demand
            guard(f"speculative_generate ({name})")
        _check_decode_mesh(m, mesh, what="speculative_generate",
                           who=name)
        if getattr(m, "sliding_window", None) is not None:
            from .rolling import ROLLING_SLACK
            if k + 1 > ROLLING_SLACK:
                raise ValueError(
                    f"speculative k={k} with a sliding-window {name}: "
                    f"rejected chunks up to k+1 tokens must fit the "
                    f"rolling cache's rewind margin "
                    f"(ROLLING_SLACK={ROLLING_SLACK}, "
                    f"inference/rolling.py) — use k <= "
                    f"{ROLLING_SLACK - 1}")
    if mesh is not None and not (_sharded_decode_axes(target)
                                 or _sharded_decode_axes(draft)):
        raise ValueError(
            "mesh was passed but neither target nor draft has a "
            "tp_axis/moe_axis/sp_axis — single-shard speculative "
            "decode needs no mesh")
    b, p = prompt_ids.shape
    if p < 1:
        raise ValueError("prompt must hold at least one token")
    s_total = p + max_new_tokens
    # chunk writes may touch up to k+1 positions past the last needed
    # one on already-finished rows; pad the buffers so they stay in
    # bounds (extra slots are never emitted)
    s_buf = s_total + k + 1
    for name, m in (("target", target), ("draft", draft)):
        if s_buf > m.max_positions:
            raise ValueError(
                f"{name}.max_positions ({m.max_positions}) < prompt + "
                f"max_new_tokens + k + 1 ({s_buf}) — speculative "
                f"verification needs k+1 slack positions")

    t_params = [q for q in target.parameters()] + list(target.buffers())
    d_params = [q for q in draft.parameters()] + list(draft.buffers())
    t_vals = [q.data for q in t_params]
    d_vals = [q.data for q in d_params]

    def run(t_vals, d_vals, prompt_ids, key):
        t_ctx = Ctx(env={id(o): v for o, v in zip(t_params, t_vals)},
                    stats_out={}, training=False)
        d_ctx = Ctx(env={id(o): v for o, v in zip(d_params, d_vals)},
                    stats_out={}, training=False)
        # cache dtypes default per model to the embedding dtype, the
        # same rule generate() uses — the exactness guarantee compares
        # against generate(target), so the target must score through
        # identically-typed caches
        t_dtype = cache_dtype or target.tok_emb.weight.data.dtype
        d_dtype = cache_dtype or draft.tok_emb.weight.data.dtype
        t_caches = target.init_caches(b, s_buf, dtype=t_dtype)
        d_caches = draft.init_caches(b, s_buf, dtype=d_dtype)

        ids = jnp.concatenate(
            [prompt_ids, jnp.zeros((b, s_buf - p), prompt_ids.dtype)],
            axis=1)

        # prefill both models on the prompt (flash path, same program
        # generate() prefills with; a 1-token prompt goes through
        # decode_chunk — generate() keeps the step path there too);
        # token at position p is the target's continuation
        if p > 1:
            t_logits, t_caches = target.prefill(t_ctx, ids[:, :p],
                                                t_caches)
            _, d_caches = draft.prefill(d_ctx, ids[:, :p], d_caches)
        else:
            t_logits, t_caches = target.decode_chunk(
                t_ctx, ids[:, :1], t_caches, jnp.int32(0))
            _, d_caches = draft.decode_chunk(
                d_ctx, ids[:, :1], d_caches, jnp.int32(0))
        if sampled:
            key, sub = jax.random.split(key)
            first = jax.random.categorical(
                sub, t_logits[:, -1].astype(jnp.float32) / temperature,
                axis=-1).astype(ids.dtype)
        else:
            first = jnp.argmax(t_logits[:, -1], axis=-1).astype(ids.dtype)
        ids = jax.lax.dynamic_update_slice(ids, first[:, None], (0, p))

        # m: position of the last known-but-unfed token (scalar — the
        # batch is lockstep); tokens are needed through s_total - 1
        m0 = jnp.int32(p)

        def cond(carry):
            ids, m, t_caches, d_caches, key, rounds = carry
            return m < s_total - 1

        def body(carry):
            ids, m, t_caches, d_caches, key, rounds = carry
            # per-round randomness derived from the position so the
            # program is replay-stable
            round_key = jax.random.fold_in(key, m)

            # --- draft proposes k tokens (k+1 single steps feeding its
            #     own argmax chain from ids[:, m], so its cache also
            #     covers position m+k for the all-accepted case) ---
            def d_step(carry, skey):
                tok, d_caches, t = carry
                logits, d_caches = draft.decode_step(d_ctx, tok, d_caches,
                                                     t)
                if sampled:
                    probs = jax.nn.softmax(
                        logits.astype(jnp.float32) / temperature, axis=-1)
                    nxt = jax.random.categorical(
                        skey, logits.astype(jnp.float32) / temperature,
                        axis=-1).astype(ids.dtype)
                else:
                    probs = jnp.zeros_like(logits, jnp.float32)
                    nxt = jnp.argmax(logits, axis=-1).astype(ids.dtype)
                return (nxt, d_caches, t + 1), (nxt, probs)

            tok0 = jax.lax.dynamic_slice(ids, (0, m), (b, 1))[:, 0]
            d_keys = jax.random.split(
                jax.random.fold_in(round_key, 0), k + 1)
            (_, d_caches, _), (props, d_probs) = jax.lax.scan(
                d_step, (tok0, d_caches, m), d_keys)
            drafts = jnp.swapaxes(props, 0, 1)[:, :k]   # (B, k) d_1..d_k

            # --- target verifies [ids[m], d_1..d_k] in one chunk ---
            chunk = jnp.concatenate([tok0[:, None], drafts], axis=1)
            t_logits, t_caches = target.decode_chunk(
                t_ctx, chunk, t_caches, m)
            if sampled:
                # Leviathan rejection: accept d_i with min(1, p_t/p_d);
                # on the first rejection resample from the normalized
                # residual; all-accepted earns a bonus sample from the
                # target's next-position distribution.  (batch == 1)
                p_t = jax.nn.softmax(
                    t_logits[0].astype(jnp.float32) / temperature,
                    axis=-1)                            # (k+1, V)
                p_d = d_probs[:, 0, :]                  # (k+1, V) rows 0..k
                d_row = drafts[0]                       # (k,)
                pos_i = jnp.arange(k)
                ratio = p_t[pos_i, d_row] / jnp.maximum(
                    p_d[pos_i, d_row], 1e-20)
                u = jax.random.uniform(
                    jax.random.fold_in(round_key, 1), (k,))
                accept = u < jnp.minimum(ratio, 1.0)
                acc0 = jnp.argmin(jnp.concatenate(
                    [accept, jnp.zeros((1,), bool)]).astype(jnp.int32))
                # per-position replacement samples: residual at 0..k-1,
                # the bonus target distribution at position k.  Where
                # the residual is identically zero (p_t == p_d) the
                # acceptance probability was 1, so the sample is never
                # selected — the uniform fallback inside log(0+tiny)
                # never escapes the where.
                res = jnp.maximum(p_t[:k] - p_d[:k], 0.0)
                res_dist = jnp.concatenate([res, p_t[k:]], axis=0)
                r_keys = jax.random.split(
                    jax.random.fold_in(round_key, 2), k + 1)
                res_samples = jax.vmap(
                    lambda kk, d: jax.random.categorical(
                        kk, jnp.log(d + 1e-30)))(r_keys, res_dist)
                emit = jnp.where(jnp.arange(k + 1) == acc0,
                                 res_samples.astype(ids.dtype),
                                 jnp.concatenate(
                                     [d_row, d_row[-1:]]).astype(
                                     ids.dtype))
                merged = emit[None, :]
                n_round = acc0 + 1
            else:
                greedy = jnp.argmax(t_logits, axis=-1).astype(ids.dtype)
                # longest prefix where draft == target argmax, per row;
                # the lockstep advance is the batch minimum
                agree = drafts == greedy[:, :k]
                acc = jnp.argmin(
                    jnp.concatenate([agree, jnp.zeros((b, 1), bool)],
                                    axis=1).astype(jnp.int32), axis=1)
                n_round = jnp.min(acc) + 1              # in [1, k+1]
                merged = greedy
            # emit merged[:, :n_round] — beyond it, keep what is there
            cur = jax.lax.dynamic_slice(ids, (0, m + 1), (b, k + 1))
            merged = jnp.where(
                jnp.arange(k + 1)[None, :] < n_round, merged, cur)
            ids = jax.lax.dynamic_update_slice(ids, merged, (0, m + 1))
            return ids, jnp.minimum(m + n_round, s_total - 1), \
                t_caches, d_caches, key, rounds + 1

        ids, _, _, _, _, rounds = jax.lax.while_loop(
            cond, body, (ids, m0, t_caches, d_caches, key,
                         jnp.zeros((), jnp.int32)))
        return ids[:, :s_total], rounds

    # per-model compiled-run cache (see utils/jit_cache.py for the
    # parameter-identity/LRU invariants); each entry's closure pins its
    # draft module and XLA executable, so the cap (8: spec programs are
    # large) keeps a loop trying many drafts against one target from
    # accumulating them all for the target's lifetime
    from ..utils.jit_cache import compiled_run_cache

    def build():
        if mesh is not None:
            # whole program replicated in/out, exactly generate()'s TP
            # convention: the tp model(s) slice their head blocks at
            # trace time, row-parallel psums leave every logit
            # replicated, and an unsharded counterpart model simply
            # computes replicated inside the same region
            from jax.sharding import PartitionSpec as _P
            from ..compat import shard_map as _shard_map
            return jax.jit(_shard_map(
                run, mesh=mesh, in_specs=(_P(), _P(), _P(), _P()),
                out_specs=(_P(), _P()), check_vma=False))
        return jax.jit(run)

    fn = compiled_run_cache(
        target, "_spec_jit_cache",
        (id(draft), b, p, max_new_tokens, k, float(temperature),
         None if cache_dtype is None
         else cache_dtype if isinstance(cache_dtype, str)
         else jnp.dtype(cache_dtype).name,
         mesh),
        t_params + d_params, build, cap=8)
    ids, rounds = fn(t_vals, d_vals, prompt_ids, key)
    if return_stats:
        # rounds is a traced-by-product scalar: fetching it syncs, which
        # the stats path accepts (callers timing pure decode leave
        # return_stats off and never pay the fetch).  The FIRST new
        # token comes from the prefill argmax before the loop, so the
        # verification rounds produce max_new_tokens - 1 tokens; the
        # final round's tail clamp makes the derived acceptance a floor.
        r = int(rounds)
        tpr = (max_new_tokens - 1) / max(r, 1)
        return ids, {
            "rounds": r,
            "tokens_per_round": tpr,
            # per round the target contributes 1 token regardless; the
            # rest are accepted draft proposals out of k offered
            "draft_acceptance": (tpr - 1.0) / k if k else 0.0,
        }
    return ids
