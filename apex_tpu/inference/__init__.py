"""Inference-side utilities: weight-only int8 quantization for the
bandwidth-bound decode path (see quant.py for the rationale)."""
from .quant import (QuantTensor, quantize_int8,  # noqa: F401
                    quantize_tensor_int8)
