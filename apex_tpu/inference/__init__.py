"""Inference-side utilities: weight-only int8 quantization for the
bandwidth-bound decode path (quant.py), draft-verified greedy
speculative decoding (speculative.py), beam search (beam.py), the
rolling sliding-window KV cache (rolling.py), and stateful multi-turn
decode sessions (session.py)."""
from .beam import beam_generate  # noqa: F401
from .session import DecodeSession, PagedSession  # noqa: F401
from .quant import (QuantKV, QuantTensor, absmax_int8,  # noqa: F401
                    gather_rows, kv_value, kv_write, make_kv_cache,
                    quantize_int8, quantize_tensor_int8)
from .speculative import speculative_generate  # noqa: F401
