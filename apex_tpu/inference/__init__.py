"""Inference-side utilities: weight-only int8 quantization for the
bandwidth-bound decode path (quant.py), draft-verified greedy
speculative decoding (speculative.py) with draft construction and
distillation (draft.py), beam search (beam.py), the rolling
sliding-window KV cache (rolling.py), and stateful multi-turn decode
sessions (session.py).  This surface is the package boundary: the
serve engine consumes speculation through these names, never through
module internals."""
from .beam import beam_generate  # noqa: F401
from .draft import make_self_draft, train_draft  # noqa: F401
from .session import DecodeSession, PagedSession  # noqa: F401
from .quant import (QuantKV, QuantTensor, absmax_int8,  # noqa: F401
                    gather_rows, kv_value, kv_write, make_kv_cache,
                    quantize_int8, quantize_tensor_int8)
from .speculative import speculative_generate  # noqa: F401
