"""Fused multi-model/multi-loss train step: the GAN iteration.

The reference exercises its multi-model amp surface through DCGAN
(examples/dcgan/main_amp.py:214-253: ``amp.initialize([netD, netG],
[optD, optG], num_losses=3)`` with per-loss ``loss_id``) on the imperative
path.  This module is the fused-path equivalent: the full alternating
iteration —

1. ``fake = netG(z)`` (one generator forward),
2. discriminator step: grads of ``d_loss_fn(netD(real), netD(sg(fake)))``
   w.r.t. D only, fused optimizer update, per-loss scaler,
3. generator step: grads of ``g_loss_fn(netD'(fake))`` w.r.t. G, flowing
   through the *updated* discriminator (the reference ordering: errG is
   computed after optimizerD.step()),

— compiles into ONE XLA executable.  XLA CSEs the two generator forwards
(same params, same z, same dropout key), so the compiled graph runs G once.
Each network has its own loss scaler and skip-step, like the reference's
per-loss scalers; an overflow in D leaves D unchanged but the G step still
runs against the old D.
"""
from __future__ import annotations

import itertools
import time
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..nn.modules import Ctx
from .step import (StepState, apply_fused_update, build_opt_update,
                   init_step_state, match_param_groups, model_vals_of,
                   _model_dtypes)


#: per-builder token in the executor program key (two GAN steps with
#: identical signatures close over different nets/losses)
_GAN_TOKENS = itertools.count()


class GanStepState(NamedTuple):
    d: StepState
    g: StepState


class GanTrainStep:
    """Built by :func:`make_gan_train_step`."""

    def __init__(self, netD, netG, optD, optG, step_fn, d_parts, g_parts,
                 init_state):
        self.netD, self.netG = netD, netG
        self.optD, self.optG = optD, optG
        self._step_fn = step_fn
        self._d_parts, self._g_parts = d_parts, g_parts
        self.state = init_state
        self.compile_s = None

    def __call__(self, real, z):
        t0 = time.perf_counter() if self.compile_s is None else None
        self.state, losses = self._step_fn(self.state, real, z)
        if t0 is not None:
            self.compile_s = time.perf_counter() - t0
        return losses

    def sync_to_objects(self):
        for (params, buffers), sub in ((self._d_parts, self.state.d),
                                       (self._g_parts, self.state.g)):
            for i, (p, v) in enumerate(zip(params, sub.model_params)):
                p.data = sub.master_params[i] if v is None else v
            for b, v in zip(buffers, sub.stats):
                b.data = v


def _net_parts(model, optimizer, half_dtype, keep_batchnorm_fp32, caller):
    params = [p for p in model.parameters() if p is not None]
    buffers = [b for b in model.buffers()]
    group_idxs = match_param_groups(optimizer, params, caller=caller)
    dtypes = _model_dtypes(model, params, half_dtype, keep_batchnorm_fp32)
    opt_update, opt_init = build_opt_update(optimizer, params, group_idxs,
                                            caller=caller)
    return params, buffers, dtypes, opt_update, opt_init


def make_gan_train_step(netD, netG, optD, optG,
                        d_loss_fn: Callable, g_loss_fn: Callable,
                        half_dtype=None,
                        keep_batchnorm_fp32: bool = True,
                        loss_scale: float | str = "dynamic",
                        scale_window: int = 2000,
                        min_loss_scale: Optional[float] = None,
                        max_loss_scale: float = 2.0 ** 24,
                        donate_state="auto",
                        lr_schedule: Optional[Callable] = None,
                        rng_seed: int = 0):
    """Build the fused GAN iteration.

    ``d_loss_fn(d_real_out, d_fake_out) -> scalar`` and
    ``g_loss_fn(d_fake_out) -> scalar`` (e.g. BCE against real/fake labels).
    The step signature is ``step(state, real_batch, z) -> (state,
    (errD, errG))``.  ``lr_schedule`` applies to both optimizers from
    each network's own step counter (as in make_train_step).
    """
    from ..runtime import executor as _executor
    # the executor's donation policy: donate on tpu/gpu, skip on cpu
    # (defensive copies + the jax-0.4.x cached-executable aliasing
    # hazard — see make_train_step's donate_state doc)
    donate_state = _executor.donation.resolve(donate_state)
    d_parts = _net_parts(netD, optD, half_dtype, keep_batchnorm_fp32,
                         "make_gan_train_step(netD)")
    g_parts = _net_parts(netG, optG, half_dtype, keep_batchnorm_fp32,
                         "make_gan_train_step(netG)")
    d_params, d_buffers, d_dtypes, d_update, d_opt_init = d_parts
    g_params, g_buffers, g_dtypes, g_update, g_opt_init = g_parts

    dynamic = loss_scale == "dynamic"
    init_scale = (min(max_loss_scale, 2.0 ** 16) if dynamic
                  else float(loss_scale))

    def _run(model, params, buffers, param_vals, stats, x, key,
             training=True):
        """One pure forward; returns (out, new_stats)."""
        env = {id(p): v for p, v in zip(params, param_vals)}
        env.update({id(b): v for b, v in zip(buffers, stats)})
        stats_out = {}
        ctx = Ctx(env=env, stats_out=stats_out, training=training, key=key)
        out = model.forward(ctx, x)
        new_stats = [stats_out.get(id(b), sv)
                     for b, sv in zip(buffers, stats)]
        return out, new_stats

    def _finish_update(sub: StepState, grads, opt_update, dtypes):
        return apply_fused_update(
            sub, grads, opt_update, dtypes, dynamic=dynamic,
            init_scale=init_scale, scale_window=scale_window,
            min_loss_scale=min_loss_scale, max_loss_scale=max_loss_scale,
            lr_schedule=lr_schedule)

    def step_fn(state: GanStepState, real, z):
        d, g = state.d, state.g
        base = jax.random.PRNGKey(rng_seed)
        g_key = jax.random.fold_in(base, g.step * 2)
        # the three discriminator forwards (real, detached fake, G-step)
        # each get their own key so a D with Dropout draws independent
        # masks per call, matching the imperative path's fresh key per
        # module call
        d_base = jax.random.fold_in(base, d.step * 2 + 1)
        d_key_real = jax.random.fold_in(d_base, 0)
        d_key_fake = jax.random.fold_in(d_base, 1)
        d_key_gstep = jax.random.fold_in(d_base, 2)

        if half_dtype is not None:
            if jnp.issubdtype(real.dtype, jnp.floating):
                real = real.astype(half_dtype)
            if jnp.issubdtype(z.dtype, jnp.floating):
                z = z.astype(half_dtype)

        g_vals = model_vals_of(g)
        d_vals = model_vals_of(d)

        # 1) generator forward (no grad; CSE'd with the G-step's forward)
        fake, _ = _run(netG, g_params, g_buffers, g_vals, g.stats, z, g_key)
        fake = jax.lax.stop_gradient(fake)

        # 2) discriminator step on real + detached fake
        def d_forward(d_vals_in):
            out_r, stats1 = _run(netD, d_params, d_buffers, d_vals_in,
                                 d.stats, real, d_key_real)
            out_f, stats2 = _run(netD, d_params, d_buffers, d_vals_in,
                                 stats1, fake, d_key_fake)
            errD = d_loss_fn(out_r, out_f)
            return errD.astype(jnp.float32) * d.scaler.loss_scale, \
                (errD, stats2)

        (_, (errD, d_stats)), d_grads = jax.value_and_grad(
            d_forward, has_aux=True)(d_vals)
        d_new = _finish_update(d._replace(stats=d_stats), d_grads,
                               d_update, d_dtypes)

        # 3) generator step through the UPDATED discriminator (reference
        # ordering: errG after optimizerD.step())
        d_vals_new = model_vals_of(d_new)

        def g_forward(g_vals_in):
            fake2, g_stats = _run(netG, g_params, g_buffers, g_vals_in,
                                  g.stats, z, g_key)
            out_f, d_stats2 = _run(netD, d_params, d_buffers, d_vals_new,
                                   d_new.stats, fake2, d_key_gstep)
            errG = g_loss_fn(out_f)
            return errG.astype(jnp.float32) * g.scaler.loss_scale, \
                (errG, g_stats, d_stats2)

        (_, (errG, g_stats, d_stats2)), g_grads = jax.value_and_grad(
            g_forward, has_aux=True)(g_vals)
        g_new = _finish_update(g._replace(stats=g_stats), g_grads,
                               g_update, g_dtypes)
        d_new = d_new._replace(stats=d_stats2)

        return GanStepState(d_new, g_new), (errD, errG)

    init_state = GanStepState(
        d=init_step_state(d_params, d_buffers, d_dtypes, d_opt_init,
                          init_scale),
        g=init_step_state(g_params, g_buffers, g_dtypes, g_opt_init,
                          init_scale))

    # the GAN iteration dispatches through the runtime executor like
    # every other step kind: cached compile, dispatch span + counters,
    # watchdog heartbeats
    program = _executor.Program(
        "gan_train_step", (next(_GAN_TOKENS), bool(donate_state)), step_fn,
        donate_argnums=(0,) if donate_state else ())
    dispatch_no = itertools.count(1)

    def jit_step(state, real, z):
        return _executor.executor.submit(
            program, (state, real, z), step=next(dispatch_no))

    return GanTrainStep(netD, netG, optD, optG, jit_step,
                        (d_params, d_buffers), (g_params, g_buffers),
                        init_state)
