from .step import StepState, TrainStep, make_train_step  # noqa: F401
