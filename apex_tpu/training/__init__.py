from .step import StepState, TrainStep, make_train_step  # noqa: F401
from .gan import GanStepState, GanTrainStep, make_gan_train_step  # noqa: F401
