"""Fused train-step builder: the TPU-native fast path.

Where the reference's hot loop is Python driving kernels (SURVEY.md §3.2),
here the entire iteration — forward, backward, unscale + overflow check,
conditional skip, optimizer update, loss-scale update, BN running stats —
compiles into ONE XLA executable with zero host round-trips.  The stateful
facade (model/optimizer/scaler objects) is synchronized from the returned
device state, so the imperative API and the fused path are interchangeable.

This is the path ``bench.py``, the examples and DistributedDataParallel use;
``amp.scale_loss`` + ``loss.backward()`` (apex_tpu.autograd) is the
API-parity path.
"""
from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..amp.scaler import ScalerState, update_scale_state
from ..nn.modules import Ctx
from ..nn.parameter import Parameter


class StepState(NamedTuple):
    """Device-side training state for the fused step."""
    master_params: list          # fp32 masters (or the params themselves)
    model_params: list           # half copies fed to forward (may be same)
    opt_state: dict              # optimizer slots, name -> list
    scaler: ScalerState
    stats: list                  # module buffer values (BN running stats)
    step: jax.Array              # i32


class TrainStep:
    """Built by :func:`make_train_step`; owns the compiled step and the
    object<->state synchronization."""

    def __init__(self, model, optimizer, loss_fn, step_fn, params, buffers,
                 init_state):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._step_fn = step_fn
        self._params = params
        self._buffers = buffers
        self.state = init_state

    def __call__(self, *batch):
        self.state, loss = self._step_fn(self.state, *batch)
        return loss

    def sync_to_objects(self):
        """Write device state back into the model/scaler objects.

        The optimizer's param_groups reference the SAME Parameter objects as
        the model (make_train_step never swaps masters in), so each param
        gets its model-dtype value (half where cast, else the fp32 master);
        the fp32 masters live in ``self.state.master_params``.
        """
        st = self.state
        for i, (p, v) in enumerate(zip(self._params, st.model_params)):
            p.data = st.master_params[i] if v is None else v
        for b, v in zip(self._buffers, st.stats):
            b.data = v
        from ..amp._amp_state import _amp_state
        if _amp_state.loss_scalers:
            _amp_state.loss_scalers[0].state = st.scaler


def make_train_step(model, optimizer, loss_fn: Callable,
                    half_dtype=None,
                    keep_batchnorm_fp32: bool = True,
                    dynamic_loss_scale: bool = True,
                    scale_window: int = 2000,
                    min_loss_scale: Optional[float] = None,
                    max_loss_scale: float = 2.0 ** 24,
                    loss_scale: float | str = "dynamic",
                    axis_name: Optional[str] = None,
                    gradient_predivide_factor: float = 1.0,
                    allreduce_always_fp32: bool = False,
                    donate_state: bool = True):
    """Build a fully-fused O2-style train step.

    ``loss_fn(outputs..., *batch_tail) -> scalar``: called with the model
    output.  The step signature is ``step(state, *batch) -> (state, loss)``
    where ``batch[0]`` feeds the model and the full batch feeds ``loss_fn``.

    When ``axis_name`` is given the step is meant to run under
    ``shard_map``/``pjit`` over that mesh axis: gradients are psum-averaged
    with the reference DDP's knobs honored (``gradient_predivide_factor``
    splits the averaging before/after the all-reduce,
    apex/parallel/distributed.py:445-454; ``allreduce_always_fp32`` casts
    grads to fp32 for the collective, :417-421).
    """
    from ..optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD
    from .. import ops

    params = [p for p in model.parameters() if p is not None]
    buffers = [b for b in model.buffers()]
    from ..nn.modules import _BatchNorm

    bn_param_ids = set()
    if keep_batchnorm_fp32:
        for m in model.modules():
            if isinstance(m, _BatchNorm):
                for p in m._parameters.values():
                    if p is not None:
                        bn_param_ids.add(id(p))

    if half_dtype is None:
        model_dtypes = [p.data.dtype for p in params]
    else:
        model_dtypes = [
            jnp.float32 if id(p) in bn_param_ids else jnp.dtype(half_dtype)
            for p in params]

    dynamic = loss_scale == "dynamic"
    init_scale = (min(max_loss_scale, 2.0 ** 16) if dynamic
                  else float(loss_scale))

    # map optimizer type -> pure update over flat lists
    opt = optimizer
    if isinstance(opt, FusedSGD):
        group = opt.param_groups[0]
        mom = group["momentum"]

        def opt_update(flag, grads, masters, slots, step):
            flag, new_p, new_m = ops.multi_tensor_sgd(
                flag, [grads, masters, slots["momentum"]],
                group["weight_decay"], mom, group["dampening"], group["lr"],
                group["nesterov"], False, opt.wd_after_momentum, 1.0)
            return new_p, {"momentum": new_m}

        def opt_init():
            return {"momentum": [jnp.zeros(p.shape, jnp.float32)
                                 for p in params]}
    elif isinstance(opt, FusedAdam):
        group = opt.param_groups[0]
        b1, b2 = group["betas"]

        def opt_update(flag, grads, masters, slots, step):
            _, new_p, new_m, new_v = ops.multi_tensor_adam(
                flag, [grads, masters, slots["m"], slots["v"]],
                group["lr"], b1, b2, group["eps"], step, opt.adam_w_mode,
                bool(group["bias_correction"]), group["weight_decay"])
            return new_p, {"m": new_m, "v": new_v}

        def opt_init():
            z = [jnp.zeros(p.shape, jnp.float32) for p in params]
            return {"m": z, "v": [jnp.zeros(p.shape, jnp.float32)
                                  for p in params]}
    elif isinstance(opt, FusedLAMB):
        group = opt.param_groups[0]
        b1, b2 = group["betas"]

        def opt_update(flag, grads, masters, slots, step):
            _, gnorm, _ = ops.multi_tensor_l2norm(flag, [grads])
            _, new_p, new_m, new_v = ops.multi_tensor_lamb(
                flag, [grads, masters, slots["m"], slots["v"]],
                group["lr"], b1, b2, group["eps"], step,
                bool(group["bias_correction"]), group["weight_decay"],
                1 if group["grad_averaging"] else 0, opt.adam_w_mode,
                gnorm, group["max_grad_norm"])
            return new_p, {"m": new_m, "v": new_v}

        def opt_init():
            z = [jnp.zeros(p.shape, jnp.float32) for p in params]
            return {"m": z, "v": [jnp.zeros(p.shape, jnp.float32)
                                  for p in params]}
    else:
        raise TypeError(f"make_train_step does not support {type(opt)}")

    def _model_vals(masters, model_params):
        # model_params holds None where no cast is needed (sharing the master
        # buffer would double-donate under buffer donation)
        return [masters[i] if mp is None else mp
                for i, mp in enumerate(model_params)]

    def step_fn(state: StepState, *batch):
        model_vals = _model_vals(state.master_params, state.model_params)

        def forward(model_vals_in, *b):
            env = {id(p): v for p, v in zip(params, model_vals_in)}
            stats_env = {id(bf): v for bf, v in zip(buffers, state.stats)}
            stats_out = {}
            ctx = Ctx(env={**env, **stats_env}, stats_out=stats_out,
                      training=True)
            x = b[0]
            if half_dtype is not None and jnp.issubdtype(x.dtype,
                                                         jnp.floating):
                # O2 input cast (reference patches model.forward to cast
                # incoming data, _initialize.py:194-201)
                x = x.astype(half_dtype)
            out = model.forward(ctx, x)
            loss = loss_fn(out, *b[1:])
            new_stats = [stats_out.get(id(bf), sv)
                         for bf, sv in zip(buffers, state.stats)]
            return loss.astype(jnp.float32) * state.scaler.loss_scale, \
                (loss, new_stats)

        (scaled_loss, (loss, new_stats)), grads = jax.value_and_grad(
            forward, has_aux=True)(model_vals, *batch)

        # DP gradient exchange (psum over the mapped axis), with DDP knobs
        if axis_name is not None:
            n = jax.lax.axis_size(axis_name)
            pre = gradient_predivide_factor
            post = n / gradient_predivide_factor

            def exchange(g):
                gc = g.astype(jnp.float32) if allreduce_always_fp32 else g
                gc = gc / pre if pre != 1.0 else gc
                gc = jax.lax.psum(gc, axis_name)
                gc = gc / post
                return gc.astype(g.dtype) if allreduce_always_fp32 else gc
            grads = [exchange(g) for g in grads]

        # unscale into fp32 master grads + overflow flag
        inv = 1.0 / state.scaler.loss_scale
        flag = jnp.zeros((), jnp.int32)
        master_grads = []
        for g in grads:
            gf = g.astype(jnp.float32) * inv
            flag = jnp.maximum(flag, (~jnp.isfinite(gf)).any()
                               .astype(jnp.int32))
            master_grads.append(gf)

        step_count = state.step + 1
        new_masters, new_slots = opt_update(
            flag, master_grads, state.master_params, state.opt_state,
            step_count)

        # skip-step on overflow: keep old state (lax.select keeps it fused)
        skip = flag > 0
        sel = functools.partial(jnp.where, skip)
        masters = [sel(o, n) for o, n in zip(state.master_params, new_masters)]
        slots = {k: [sel(o, n) for o, n in zip(state.opt_state[k],
                                               new_slots[k])]
                 for k in new_slots}
        model_params = [
            None if jnp.dtype(d) == jnp.dtype(jnp.float32) else m.astype(d)
            for m, d in zip(masters, model_dtypes)]
        step_count = jnp.where(skip, state.step, step_count)

        scaler_state = ScalerState(state.scaler.loss_scale,
                                   state.scaler.unskipped, flag)
        new_scaler, _ = update_scale_state(
            scaler_state, dynamic=dynamic, scale_window=scale_window,
            min_loss_scale=min_loss_scale, max_loss_scale=max_loss_scale)

        return StepState(masters, model_params, slots, new_scaler,
                         new_stats, step_count), loss

    masters0 = [p.data.astype(jnp.float32) for p in params]
    init_state = StepState(
        master_params=masters0,
        model_params=[
            None if jnp.dtype(d) == jnp.dtype(jnp.float32)
            else m.astype(d) for m, d in zip(masters0, model_dtypes)],
        opt_state=opt_init(),
        scaler=ScalerState(jnp.asarray(init_scale, jnp.float32),
                           jnp.zeros((), jnp.int32),
                           jnp.zeros((), jnp.int32)),
        stats=[b.data for b in buffers],
        step=jnp.zeros((), jnp.int32))

    if axis_name is None:
        jit_step = jax.jit(step_fn,
                           donate_argnums=(0,) if donate_state else ())
    else:
        jit_step = step_fn  # caller wraps in shard_map/pjit

    return TrainStep(model, optimizer, loss_fn, jit_step, params, buffers,
                     init_state)
